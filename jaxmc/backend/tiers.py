r"""Out-of-core hierarchical seen set: the host-RAM and disk cold tiers.

Every engine before this PR rolled into truncation (or unbounded device
growth) when the seen set outgrew device memory.  TLC solved the same
wall with a disk-backed fingerprint set (Yu, Manolios & Lamport, *Model
Checking TLA+ Specifications*, 1999); our rank-merge sorted-prefix
invariant (PRs 10-11) is already a merge of sorted runs, which is
exactly the primitive an LSM-style tier hierarchy (O'Neil et al., *The
Log-Structured Merge-Tree*, 1996) wants.  The ladder:

    device   the engine's sorted seen table (hot tier) — rank-merge
             dedups the <=R incoming keys per level exactly as before
    host     immutable sorted key runs in RAM (spilled device prefixes)
    disk     immutable sorted .npy runs under a spill directory,
             probed through np.memmap (never fully resident)

When the device table would outgrow its cap, the engine spills its
WHOLE sorted valid prefix here as one immutable run and restarts the
table empty; per-level survivors of the device rank-merge are then
membership-probed against the cold runs (vectorized binary search per
run) before they are counted distinct or explored.  Runs compact
LSM-style with the SAME rank-merge row discipline as the device kernel
(`_np_rank_merge` mirrors bfs._rank_merge's lower-bound + histogram
scatter, host-side via numpy), and the host tier flushes to disk when
it outgrows its key budget.

Key order: rows of int32 words compared signed-lexicographically — the
device sort order.  `_keyview` maps that order monotonically onto
unsigned big-endian bytes so np.searchsorted over a void view probes
whole rows at once (memmap-friendly: disk runs are never copied in).

Failure containment: a disk write that fails (ENOSPC, a dead mount, or
the `tier_io_error` fault site) DEGRADES the store to host-tier-only
with a named `tier.io_degraded` event — the search keeps its exact
counts and simply stops using the disk rung.
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import faults, obs


def _to_keybytes(a: np.ndarray) -> np.ndarray:
    """[n, kd] int32 rows -> [n, kd] big-endian uint32 whose raw byte
    order equals the rows' signed-lexicographic order (the device sort
    order): bias each word by 2^31, store big-endian.  Disk runs are
    PERSISTED in this form so probes binary-search the memmap directly
    — the run is never materialized in RAM."""
    a = np.ascontiguousarray(a, np.int32)
    b = (a.view(np.uint32) ^ np.uint32(0x80000000)).astype(">u4")
    return np.ascontiguousarray(b)


def _from_keybytes(kb: np.ndarray) -> np.ndarray:
    """Inverse of _to_keybytes: [n, kd] big-endian uint32 -> int32
    rows (used when a checkpoint inlines disk runs)."""
    u = np.ascontiguousarray(np.asarray(kb).astype("=u4"))
    return (u ^ np.uint32(0x80000000)).view(np.int32)


def _rowview(b: np.ndarray) -> np.ndarray:
    """[n, kd] keybyte array (possibly a memmap) -> [n] void scalars,
    one opaque 4*kd-byte row each — a VIEW, no copy, so searchsorted
    over a memmapped disk run touches only O(log n) pages."""
    return b.view(np.dtype((np.void, b.shape[1] * 4))).reshape(-1)


def _keyview(a: np.ndarray) -> np.ndarray:
    """[n, kd] int32 rows -> [n] void scalars whose unsigned byte order
    equals the rows' signed-lexicographic order (the device sort
    order)."""
    return _rowview(_to_keybytes(a))


def _merge_sorted(a: np.ndarray, b: np.ndarray,
                  va: np.ndarray, vb: np.ndarray) -> np.ndarray:
    """Merge two SORTED row arrays (given their void row views) into
    one sorted array, dropping b-rows already present in a — the
    host-side mirror of bfs._rank_merge's row discipline: one
    vectorized lower-bound per b-row, then a histogram + cumsum gives
    every a-row's shift, and two scatters build the merged run (no
    re-sort of either input).  Works on int32 rows and keybyte runs
    alike (the void view IS the sort order for both)."""
    lb = np.searchsorted(va, vb, side="left")
    found = (lb < len(a)) & (va[np.minimum(lb, len(a) - 1)] == vb)
    bnew = np.asarray(b)[~found]
    lbn = lb[~found]
    out = np.empty((len(a) + len(bnew), a.shape[1]), a.dtype)
    # pos(b_j) = lb_j + j; pos(a_i) = i + #{new b_j : lb_j <= i}
    hist = np.bincount(lbn, minlength=len(a) + 1)
    shift = np.cumsum(hist[: len(a)])
    out[np.arange(len(a)) + shift] = a
    if len(bnew):
        out[lbn + np.arange(len(bnew))] = bnew
    return out


def _np_rank_merge(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """_merge_sorted over int32 key rows."""
    if len(a) == 0:
        return b.copy()
    if len(b) == 0:
        return a.copy()
    return _merge_sorted(a, b, _keyview(a), _keyview(b))


class TieredSeen:
    """The cold (host + disk) tiers of the hierarchical seen set.

    `spill` admits one immutable sorted int32 key run ([n, key_words],
    validity lane already stripped); internally every run — host and
    disk — is held in KEYBYTE form (_to_keybytes: biased big-endian
    words whose raw byte order equals the rows' signed-lex order), so
    `probe` binary-searches each run as a zero-copy void view: no
    per-probe conversion of the host tier, O(log n) page touches per
    memmapped disk run.  `dump`/`load` serialize the whole hierarchy
    for checkpoints (int32 in the payload — portable).  All sizes are
    in KEYS; bytes = keys * key_words * 4."""

    #: host runs beyond this count compact into one (LSM fan-in)
    MAX_HOST_RUNS = 4
    #: disk runs beyond this count compact into one
    MAX_DISK_RUNS = 6

    def __init__(self, key_words: int,
                 host_budget_keys: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 log=None):
        self.key_words = int(key_words)
        env_b = os.environ.get("JAXMC_TIER_HOST_KEYS")
        self.host_budget_keys = int(
            host_budget_keys if host_budget_keys is not None
            else (env_b if env_b else 1 << 22))
        self.spill_dir = spill_dir
        self._own_dir = False
        self.log = log if log is not None else (lambda s: None)
        self.host_runs: List[np.ndarray] = []  # keybyte form
        self.disk_runs: List[str] = []
        self._disk_keys = 0
        self._run_seq = 0
        # run files referenced by the most recent path-mode checkpoint
        # (dump) or adopted from one (load): compaction must not
        # unlink a checkpoint's only copy — it retires them instead,
        # and the next dump() drops the superseded ones
        self._ckpt_refs: set = set()
        self._retired: List[str] = []
        # stats (obs gauges/counters ride these)
        self.spills = 0
        self.compactions = 0
        self.probe_wall_s = 0.0
        self.io_degraded: Optional[str] = None

    # ---- sizing ------------------------------------------------------

    @property
    def host_keys(self) -> int:
        return sum(len(r) for r in self.host_runs)

    @property
    def disk_keys(self) -> int:
        return self._disk_keys

    def __len__(self) -> int:
        return self.host_keys + self._disk_keys

    @property
    def active(self) -> bool:
        return bool(self.host_runs or self.disk_runs)

    # ---- spill / compaction ------------------------------------------

    def spill(self, run: np.ndarray) -> None:
        """Admit one immutable SORTED key run (a spilled device
        prefix).  Compacts the host tier when its run fan-in exceeds
        MAX_HOST_RUNS and flushes it to disk when it exceeds the host
        key budget."""
        run = np.ascontiguousarray(run, np.int32)
        if run.ndim != 2 or run.shape[1] != self.key_words:
            raise ValueError(
                f"tier spill: run shape {run.shape} does not match "
                f"key_words={self.key_words}")
        if len(run) == 0:
            return
        self.spills += 1
        obs.current().counter("tier.spills")
        # keybyte form once, at admission — probes then view, never
        # convert (the host tier is probed every level after a spill)
        self.host_runs.append(_to_keybytes(run))
        self.log(f"-- tier: spilled {len(run)} keys to host "
                 f"(host={self.host_keys} disk={self._disk_keys} keys)")
        if len(self.host_runs) > self.MAX_HOST_RUNS:
            self._compact_host()
        if self.host_keys > self.host_budget_keys:
            self._flush_to_disk()

    def _compact_host(self) -> None:
        merged = self.host_runs[0]
        for r in self.host_runs[1:]:
            merged = _merge_sorted(merged, r, _rowview(merged),
                                   _rowview(r))
        self.host_runs = [merged]
        self.compactions += 1
        obs.current().counter("tier.compactions")

    def _dir(self) -> str:
        if self.spill_dir is None:
            self.spill_dir = tempfile.mkdtemp(prefix="jaxmc-tiers-")
            self._own_dir = True
        os.makedirs(self.spill_dir, exist_ok=True)
        return self.spill_dir

    def _flush_to_disk(self) -> None:
        """Compact the host tier into one run and move it to disk.  A
        failed write degrades the store to host-tier-only (named event,
        exact counts preserved) — never a crash."""
        if self.io_degraded is not None:
            return
        if len(self.host_runs) > 1:
            self._compact_host()
        run = self.host_runs[0]
        self._run_seq += 1
        try:
            faults.inject("tier_io_error", op="write")
            d = self._dir()
            path = os.path.join(d, f"run{self._run_seq:05d}.npy")
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                # already keybyte: probes memmap the file directly
                np.save(fh, run)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        except (OSError, faults.FaultInjected) as ex:
            self.io_degraded = str(ex)
            obs.current().event("tier.io_degraded", error=str(ex))
            obs.current().gauge("tier.io_degraded", str(ex))
            self.log(f"WARNING: tier disk write failed ({ex}); the "
                     f"seen-set hierarchy degrades to host-tier-only — "
                     f"counts stay exact, the host RAM budget is no "
                     f"longer enforced")
            return
        self.disk_runs.append(path)
        self._disk_keys += len(run)
        self.host_runs = []
        self.log(f"-- tier: flushed {len(run)} keys to disk "
                 f"({os.path.basename(path)})")
        if len(self.disk_runs) > self.MAX_DISK_RUNS:
            self._compact_disk()

    def _compact_disk(self) -> None:
        """LSM compaction of the disk runs into one — merged directly
        in keybyte space (byte order IS row order, so the same
        rank-merge discipline applies without decoding).  Inputs are
        memmapped; the merged output materializes transiently, bounded
        by the tier size at the MAX_DISK_RUNS trigger."""
        try:
            merged = np.load(self.disk_runs[0], mmap_mode="r")
            for p in self.disk_runs[1:]:
                nxt = np.load(p, mmap_mode="r")
                merged = _merge_sorted(merged, nxt, _rowview(merged),
                                       _rowview(nxt))
            self._run_seq += 1
            d = self._dir()
            path = os.path.join(d, f"run{self._run_seq:05d}.npy")
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                np.save(fh, merged)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        except OSError as ex:
            # compaction is an optimization: keep probing the
            # uncompacted runs rather than degrade anything
            self.log(f"-- tier: disk compaction skipped ({ex})")
            return
        old = self.disk_runs
        self.disk_runs = [path]
        self._disk_keys = len(merged)
        self.compactions += 1
        obs.current().counter("tier.compactions")
        for p in old:
            if p in self._ckpt_refs:
                # the most recent (path-mode) checkpoint references
                # this file: unlinking it would make that checkpoint
                # unresumable — retire it until a newer dump()
                # supersedes the reference
                self._retired.append(p)
                continue
            try:
                os.unlink(p)
            except OSError:
                pass

    # ---- probes ------------------------------------------------------

    def probe(self, keys: np.ndarray) -> np.ndarray:
        """[n, key_words] query rows -> [n] bool, True where the key is
        present in ANY cold run (host or disk).  One vectorized binary
        search per run; disk runs stream through np.memmap."""
        keys = np.ascontiguousarray(keys, np.int32)
        n = len(keys)
        hit = np.zeros(n, bool)
        if n == 0 or not self.active:
            return hit
        t0 = time.time()
        vq = _keyview(keys)
        for run in self.host_runs:
            self._probe_view(_rowview(run), vq, hit)
        for path in self.disk_runs:
            try:
                run = np.load(path, mmap_mode="r")
            except OSError as ex:
                # an unreadable run would silently re-admit its states
                # as distinct — that is a wrong COUNT, not a degraded
                # mode, so it must surface
                raise RuntimeError(
                    f"tier disk run {path} unreadable mid-search "
                    f"({ex}); counts would no longer be exact") from ex
            # keybyte on disk: the void view is a VIEW of the memmap,
            # so each query's binary search touches O(log n) pages and
            # the run is never materialized in RAM
            self._probe_view(_rowview(run), vq, hit)
        self.probe_wall_s += time.time() - t0
        return hit

    @staticmethod
    def _probe_view(vr: np.ndarray, vq: np.ndarray,
                    hit: np.ndarray) -> None:
        miss = ~hit
        if not miss.any():
            return
        q = vq[miss]
        lb = np.searchsorted(vr, q, side="left")
        found = (lb < len(vr)) & (vr[np.minimum(lb, len(vr) - 1)] == q)
        hit[miss] = found

    # ---- checkpoint serialization ------------------------------------

    #: disk tiers up to this many keys are INLINED into checkpoints
    #: (self-contained — a resume on another host rebuilds the disk
    #: tier from the payload); past it the checkpoint references the
    #: spill-dir run files instead, so checkpointing a reference-scale
    #: out-of-core run never materializes the whole cold tier in RAM
    CKPT_INLINE_KEYS = 1 << 22

    def _ckpt_inline_keys(self) -> int:
        env = os.environ.get("JAXMC_TIER_CKPT_INLINE_KEYS")
        return int(env) if env else self.CKPT_INLINE_KEYS

    def dump(self) -> Dict[str, Any]:
        """The whole hierarchy as a picklable checkpoint payload.
        Small disk tiers are inlined (decoded back to int32 rows —
        self-contained, portable across hosts); a disk tier past the
        inline budget rides as run-file PATHS, so the periodic
        checkpoint write stays O(host tier) instead of O(disk tier) on
        exactly the runs this feature exists for (resume then needs
        the spill dir intact)."""
        out = {"key_words": self.key_words,
               "host": [_from_keybytes(r) for r in self.host_runs],
               "spills": self.spills,
               "compactions": self.compactions}
        if self._disk_keys <= self._ckpt_inline_keys():
            out["disk"] = [_from_keybytes(np.load(p, mmap_mode="r"))
                           for p in self.disk_runs]
            self._ckpt_refs = set()
        else:
            out["disk_paths"] = [os.path.abspath(p)
                                 for p in self.disk_runs]
            out["disk_keys"] = self._disk_keys
            self._ckpt_refs = set(out["disk_paths"])
        # runs a compaction retired because the PREVIOUS checkpoint
        # referenced them are superseded by this dump — drop them
        keep = []
        for p in self._retired:
            if p in self._ckpt_refs:
                keep.append(p)
                continue
            try:
                os.unlink(p)
            except OSError:
                pass
        self._retired = keep
        return out

    def load(self, payload: Dict[str, Any]) -> None:
        """Restore a dumped hierarchy: host runs verbatim; inlined
        disk runs are re-written under the (new) spill dir —
        re-materialization failures degrade to host-tier-only exactly
        like live flushes; path-referenced disk runs (a checkpoint
        past the inline budget) are re-opened and validated, with a
        NAMED error when the spill dir did not survive."""
        if payload.get("key_words") != self.key_words:
            raise ValueError(
                f"tier checkpoint has key_words="
                f"{payload.get('key_words')}, this engine uses "
                f"{self.key_words} (layout/seen-mode mismatch)")
        self.host_runs = [_to_keybytes(r)
                          for r in payload.get("host", [])]
        self.spills = int(payload.get("spills", 0))
        self.compactions = int(payload.get("compactions", 0))
        for p in payload.get("disk_paths", []):
            try:
                run = np.load(p, mmap_mode="r")
            except OSError as ex:
                raise ValueError(
                    f"tier checkpoint references disk run {p} which "
                    f"is missing/unreadable ({ex}); this checkpoint "
                    f"exceeded the inline budget "
                    f"(JAXMC_TIER_CKPT_INLINE_KEYS) and needs the "
                    f"spill directory intact to resume") from ex
            if run.ndim != 2 or run.shape[1] != self.key_words:
                raise ValueError(
                    f"tier disk run {p} has shape {run.shape}, "
                    f"expected [*, {self.key_words}]")
            self.disk_runs.append(p)
            self._disk_keys += len(run)
            # the adopted files are the source checkpoint's only
            # copies: protect them from compaction until a newer
            # dump() supersedes the reference
            self._ckpt_refs.add(os.path.abspath(p))
            # future flushes must not collide with adopted run names
            digits = "".join(ch for ch in os.path.basename(p)
                             if ch.isdigit())
            if digits:
                self._run_seq = max(self._run_seq, int(digits))
            if self.spill_dir is None:
                self.spill_dir = os.path.dirname(p)
        for run in payload.get("disk", []):
            self.host_runs.append(_to_keybytes(
                np.ascontiguousarray(run, np.int32)))
            if self.host_keys > self.host_budget_keys:
                self._flush_to_disk()
        if len(self.host_runs) > self.MAX_HOST_RUNS:
            self._compact_host()

    # ---- stats -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        out = {"host_keys": self.host_keys,
               "disk_keys": self._disk_keys,
               "host_runs": len(self.host_runs),
               "disk_runs": len(self.disk_runs),
               "spills": self.spills,
               "compactions": self.compactions,
               "probe_wall_s": round(self.probe_wall_s, 6)}
        if self.io_degraded:
            out["io_degraded"] = self.io_degraded
        return out

    def publish_gauges(self, device_keys: int = 0) -> None:
        """Stamp the tier.* observability surface (obs/schema.py)."""
        tel = obs.current()
        tel.gauge("tier.occupancy",
                  {"device": int(device_keys),
                   "host": self.host_keys, "disk": self._disk_keys})
        tel.gauge("tier.probe_wall_s", round(self.probe_wall_s, 6))
