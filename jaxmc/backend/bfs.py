r"""Device-resident BFS engine (BACKEND=jax) — SURVEY.md §7.5.

The hot loop reconstructed in SURVEY.md §3.2, as array programs: the frontier
and the seen-set live on the accelerator; one jitted level step expands every
(state x grounded action) pair with vmap, masks disabled instances, and
deduplicates by lexicographic multi-key sort (jax.lax.sort).

Two dedup modes:
  exact  (narrow layouts, W <= FP_THRESHOLD): sort keys are all W state
         lanes — zero collision risk, stronger than TLC.
  fp128  (wide layouts — raft's W is ~1-2k lanes): sort keys are four
         independent 32-bit mixes of the row (a 128-bit fingerprint, vs
         TLC's 64-bit, testout2:261-264); the collision probability is
         reported in the result like TLC reports its estimate.

Capacities are power-of-two buckets that grow on demand, so jit recompiles
O(log N) times; all shapes inside a step are static (XLA/TPU requirement).
Parent provenance rides the sorts as a non-key operand and is streamed to
host per level for counterexample reconstruction — disable with
store_trace=False for benchmark runs.
"""

from __future__ import annotations

import os
import threading
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .. import obs
from ..sem.modules import Model, satisfies_constraints
from ..sem.enumerate import enumerate_init, enumerate_next
from ..sem.eval import TLCAssertFailure, eval_expr, _bool
from ..sem.values import EvalError
from ..engine.explore import CheckResult, Violation
from ..engine.simulate import sample_states
from ..compile.vspec import Bounds, CompileError, ModeError
from ..compile.kernel2 import (KernelCtx, OV_DEMOTED, OV_PACK,
                               build_layout2, compile_action2,
                               compile_predicate2, compile_value2,
                               introspect_kernel)
from ..compile.ground import ground_arm, split_arms

SENTINEL = np.int32(2**31 - 1)
FP_THRESHOLD = 48  # lanes; beyond this, dedup on 128-bit fingerprints
# "bounds inference not yet attempted" marker for the per-model cache
# (the cached report itself may legitimately be None = analysis bailed)
_SENTINEL_NO_REPORT = object()
_POR_UNSET = object()

# resident-mode status codes (one summary scalar per dispatched batch)
ST_CONTINUE = 0     # level budget exhausted, search not finished
ST_DONE = 1         # frontier empty: search complete
ST_INV = 2          # invariant violated (aux: which, row)
ST_DEADLOCK = 3     # deadlocked state (aux: row)
ST_ASSERT = 4       # Assert failed inside an enabled action (aux: row)
ST_TRUNC = 5        # max_states reached
ST_OVF_SEEN = 6     # seen-set capacity: grow SC, redo level
ST_OVF_FRONT = 7    # frontier capacity: grow FCap, redo level
ST_OVF_ACC = 8      # level-accumulator capacity: grow AccCap, redo level
ST_OVF_VC = 9       # per-chunk valid-candidate capacity: grow VC, redo level
ST_OVF_LANES = 10   # a container outgrew its lane capacity: hard abort

SYMMETRY_WARNING = (
    "cfg SYMMETRY NOT applied on the jax backend: counts are "
    "unreduced and will exceed the interp/TLC reduced counts")

_FP_MIX = [(0x9E3779B1, 0x85EBCA6B), (0xC2B2AE35, 0x27D4EB2F),
           (0x165667B1, 0x9E3779B1), (0x85EBCA6B, 0xC2B2AE35)]


def filter_init_states(model, layout, init_rows):
    """Apply TLC's CONSTRAINT-discard semantics to encoded init rows:
    returns (explored_indices, (invariant_name, state) | None). Violating
    inits are fingerprinted by the caller but never counted distinct,
    invariant-checked, or explored; invariants run on kept inits only
    (host-side interpreter — init sets are small)."""
    from ..sem.modules import satisfies_constraints
    from ..sem.eval import eval_expr, _bool
    explored = []
    for i, row in enumerate(init_rows):
        st = layout.decode(row)
        if not satisfies_constraints(model, st):
            continue
        ctx = model.ctx(state=st)
        for nm, ex in model.invariants:
            if not _bool(eval_expr(ex, ctx), f"invariant {nm}"):
                return explored, (nm, st)
        explored.append(i)
    return explored, None


def _any_fast(x) -> bool:
    """bool(any(x)) without lifting a HOST array onto the device: the
    batched host_seen loop receives numpy step outputs (the vmapped
    dispatcher converts once for all members), and an eager jnp.any on
    those pays a host->device->host round trip PER CALL, which at
    thousands of supersteps dominated the batch win."""
    if isinstance(x, np.ndarray):
        return bool(np.any(x))
    return bool(jnp.any(x))


def _take_rows_fast(x, idx) -> np.ndarray:
    """Row-gather returning numpy: fancy-index for host arrays, device
    jnp.take (avoids transferring the full block) for device arrays."""
    if isinstance(x, np.ndarray):
        return x[idx]
    return np.asarray(jnp.take(x, jnp.asarray(idx, dtype=jnp.int32),
                               axis=0))


def _pow2_at_least(n: int, lo: int = 256) -> int:
    c = lo
    while c < n:
        c *= 2
    return c


def fingerprint128(rows):
    """rows [N, W] i32 -> [N, 4] i32 (four independent 32-bit mixes)."""
    u = rows.astype(jnp.uint32)
    out = []
    for j, (m1, m2) in enumerate(_FP_MIX):
        h = jnp.full(rows.shape[0], 2166136261 + j * 0x9E3779B1,
                     jnp.uint32)
        for i in range(rows.shape[1]):
            h = (h ^ (u[:, i] * jnp.uint32(m1))) * jnp.uint32(m2)
        h = h ^ (h >> 15)
        h = h * jnp.uint32(0x2C1B3C6D)
        h = h ^ (h >> 12)
        out.append(h.astype(jnp.int32))
    return jnp.stack(out, axis=1)


def _lower_bound(table, count, queries, cap):
    """Vectorized lexicographic lower bound: for each query row (i32
    words, signed order) the first index in table[0:count] whose row is
    not less than the query. table [cap, w]: sorted valid prefix of
    length count (traced). Fixed-trip binary search — compiles to plain
    gathers/selects (no sort comparators), safe inside while loops.

    The log2(cap) search steps MUST be a lax loop, not a Python unroll:
    unrolled, XLA's fusion pass duplicates the whole dependent
    gather/compare chain into every consumer (measured: 1 700+ copies of
    the [cap,w] gather in the optimized HLO, turning a ms-scale level
    step into minutes)."""
    n = queries.shape[0]
    iters = max(1, int(np.ceil(np.log2(max(cap, 2)))) + 1)

    def step(_, lohi):
        lo, hi = lohi
        mid = (lo + hi) // 2
        row = jnp.take(table, jnp.clip(mid, 0, cap - 1), axis=0)
        lt = jnp.zeros(n, bool)
        gt = jnp.zeros(n, bool)
        for j in range(table.shape[1]):
            undec = ~(lt | gt)
            lt = lt | (undec & (row[:, j] < queries[:, j]))
            gt = gt | (undec & (row[:, j] > queries[:, j]))
        go = lo < hi
        lo = jnp.where(go & lt, mid + 1, lo)
        hi = jnp.where(go & ~lt, mid, hi)
        return lo, hi

    lo0 = jnp.zeros(n, jnp.int32)
    hi0 = jnp.broadcast_to(jnp.asarray(count, jnp.int32), (n,))
    lo, _ = lax.fori_loop(0, iters, step, (lo0, hi0))
    return lo


def _lsd_sort(key_cols, extra_cols):
    """Stable multi-key sort as chained STABLE single-key passes (LSD
    radix over the key words, least-significant first).  Equivalent to
    `lax.sort(key_cols + extra_cols, num_keys=len(key_cols))` with
    key_cols[0] most significant — but multi-key sort comparators
    explode XLA compile time inside while loops, and both resident
    engines (single-chip tpu/bfs.py and the mesh superstep,
    tpu/mesh.py) run this under lax.while_loop.  Returns the
    (key_cols, extra_cols) lists co-sorted."""
    cols = list(key_cols) + list(extra_cols)
    nk = len(key_cols)
    for kj in range(nk - 1, -1, -1):  # least-significant first
        rest = [c for i, c in enumerate(cols) if i != kj]
        res = lax.sort(tuple([cols[kj]] + rest), num_keys=1,
                       is_stable=True)
        out_rest = list(res[1:])
        cols = [res[0] if i == kj else out_rest.pop(0)
                for i in range(len(cols))]
    return cols[:nk], cols[nk:]


def _seen_probe(seen, seen_count, keys, SC):
    """Membership of each key row in the seen table's sorted valid
    prefix — the newness verdict the rank-merge computes, exposed
    standalone so the device POR filter (ISSUE 18) can reuse it with
    zero extra dispatches.  keys [N, K] need NOT be sorted
    (_lower_bound binary-searches per query); invalid rows (validity
    lane != 0, SENTINEL words) sort past the prefix and report False.

    Returns (found [N] bool, lb [N] int32 lower-bound rank)."""
    words = keys[:, 1:]
    seen_words = seen[:, 1:]
    lb = _lower_bound(seen_words, seen_count, words, SC)
    at_lb = jnp.take(seen_words, jnp.clip(lb, 0, SC - 1), axis=0)
    found = (lb < seen_count) & jnp.all(at_lb == words, axis=1)
    return found, lb


def _por_mask(found, cvalid, inst_arm, arm_safe, A, FC):
    """Device persistent-set filter (ISSUE 18): per frontier slot f,
    pick the FIRST por-safe arm whose successor set is nonempty and
    entirely NEW — the interp's singleton-ample rule
    (engine/explore._por_expand, first arm in sorted(por_safe) order)
    — and mask every other arm's candidates for that slot; slots with
    no such arm keep full expansion.

    found/cvalid are [C = A*FC] over the dense candidate grid with
    c = a * FC + f; inst_arm [A] maps instance rows to split-arm
    indices (slotted kernels contribute n_slots rows per arm);
    arm_safe [n_arms] marks the arms the independence report proved
    globally-commuting + property-invisible.

    Soundness of probing the PRE-LEVEL seen snapshot: after level L's
    merge the table holds the closure through depth L+1, so a
    successor that probes NEW has strictly greater depth than its
    source — ample chains strictly deepen and every cycle retains a
    fully-expanded state (the BFS cycle proviso C3).  Within-level
    sibling duplicates pass the probe but are deduped by the merge,
    which only makes the filter more conservative, never unsound.
    Deadlock/assert verdicts are evaluated by callers on the PRE-mask
    enabledness, and the ample arm commutes with every arm, so
    invariant/deadlock verdicts match the unreduced run.

    Returns (keep [C] = cvalid minus masked candidates,
             n_ample  frontier slots reduced to a singleton arm,
             n_expanded  frontier slots with any enabled candidate)."""
    n_arms = arm_safe.shape[0]
    cv = cvalid.reshape(A, FC)
    bad = (found & cvalid).reshape(A, FC)
    one_hot = (jnp.arange(n_arms, dtype=jnp.int32)[:, None]
               == inst_arm[None, :]).astype(jnp.int32)   # [n_arms, A]
    en_cnt = one_hot @ cv.astype(jnp.int32)              # [n_arms, FC]
    bad_cnt = one_hot @ bad.astype(jnp.int32)
    elig = arm_safe[:, None] & (en_cnt > 0) & (bad_cnt == 0)
    has = jnp.any(elig, axis=0)                          # [FC]
    # argmax over bool returns the FIRST True: the lowest-indexed
    # eligible arm, matching the interp's sorted(por_safe) order
    chosen = jnp.argmax(elig, axis=0).astype(jnp.int32)
    keep_inst = (~has)[None, :] | \
        (inst_arm[:, None] == chosen[None, :])           # [A, FC]
    keep = keep_inst.reshape(A * FC) & cvalid
    slot_en = jnp.any(cv, axis=0)
    n_ample = jnp.sum(has & slot_en, dtype=jnp.int32)
    n_expanded = jnp.sum(slot_en, dtype=jnp.int32)
    return keep, n_ample, n_expanded


def _por_mask_np(found, cvalid, inst_arm, arm_safe, A, FC):
    """NumPy twin of _por_mask for the host_seen engine's host-side
    filter (same ample rule against the native fingerprint store)."""
    n_arms = arm_safe.shape[0]
    cv = cvalid.reshape(A, FC)
    bad = (found & cvalid).reshape(A, FC)
    one_hot = (np.arange(n_arms)[:, None] == inst_arm[None, :])
    en_cnt = one_hot.astype(np.int64) @ cv.astype(np.int64)
    bad_cnt = one_hot.astype(np.int64) @ bad.astype(np.int64)
    elig = arm_safe[:, None] & (en_cnt > 0) & (bad_cnt == 0)
    has = np.any(elig, axis=0)
    chosen = np.argmax(elig, axis=0)
    keep_inst = (~has)[None, :] | (inst_arm[:, None] == chosen[None, :])
    keep = keep_inst.reshape(A * FC) & cvalid
    slot_en = np.any(cv, axis=0)
    n_ample = int(np.sum(has & slot_en))
    n_expanded = int(np.sum(slot_en))
    return keep, n_ample, n_expanded


def _rank_merge(seen, seen_count, keys, N, SC, K, multikey=False):
    """The O(new) seen-merge core SHARED by the single-chip resident
    level and the mesh rank-merge strategy (ISSUE 10; the
    _candidate_block_fn-style shared-plumbing pattern): the seen table
    keeps a sorted valid prefix [0:seen_count) as an INVARIANT, so a
    level only sorts its ≤N incoming keys (_lsd_sort — while_loop
    safe), dedups them against the prefix with vectorized binary
    searches (_lower_bound) and scatters the genuinely-new keys at
    their ranks.  No per-level re-sort of the seen table: the sort
    work is O(N log N), not O((SC+N) log (SC+N)).

    seen [SC, K] (validity lane first, prefix sorted by the K-1 data
    words), seen_count traced scalar, keys [N, K] unsorted candidate
    keys (invalid rows: lane 0 != 0, SENTINEL data — they sort last).

    Returns dict:
      new_count  how many sorted candidate keys are genuinely new
      nk_sidx    [N] each compacted new key's ORIGINAL row index in
                 `keys` (key-sorted order; ties keep first occurrence)
      seen2      [SC, K] merged table — sorted valid prefix of length
                 seen_count + new_count, invalid tail (lane 1,
                 SENTINEL data).  Positions past SC are DROPPED: the
                 caller must treat seen_count2 > SC as an overflow and
                 roll the level back (seen_count2 still reports the
                 TRUE need, so growth can jump straight to it).
      seen_count2  seen_count + new_count (NOT cropped to SC).

    multikey=True sorts the candidate keys with ONE stable multi-key
    lax.sort instead of the LSD chain — measured 3x faster on XLA:CPU
    at mesh shapes, and a 5-key sort inside a while_loop compiles in
    well under a second on current XLA (the mesh superstep uses it);
    the single-chip resident engine keeps the LSD chain its compile
    envelope was measured with."""
    sidx = jnp.arange(N, dtype=jnp.int32)
    if multikey:
        res = lax.sort(tuple(keys[:, j] for j in range(K)) + (sidx,),
                       num_keys=K, is_stable=True)
        kc = list(res[:K])
        sidx_s = res[K]
    else:
        kc, ec = _lsd_sort([keys[:, j] for j in range(K)], [sidx])
        sidx_s = ec[0]
    skeys = jnp.stack(kc, axis=1)
    svalid = skeys[:, 0] == 0
    neq_prev = jnp.concatenate([
        jnp.array([True]),
        jnp.any(skeys[1:] != skeys[:-1], axis=1)])

    words = skeys[:, 1:]
    found, lb = _seen_probe(seen, seen_count, skeys, SC)
    new = svalid & ~found & neq_prev
    new_count = jnp.sum(new, dtype=jnp.int32)

    # compact the new keys to the front (stable: key order kept).
    # A cumsum-rank scatter, NOT a sort: the 1-key compaction sort
    # this replaces was ~0.5s per level at mesh candidate-block
    # shapes (ISSUE 11 phase-wall profile) while the scatter is tens
    # of ms — and the order is identical, because cumsum ranks
    # preserve the (already key-sorted) row order.  Dropped rows get
    # DISTINCT out-of-range indices (N + sidx): unique_indices=True
    # is a correctness promise to XLA (advisor r2 rule).
    npos = jnp.cumsum(new.astype(jnp.int32)) - 1
    tgt = jnp.where(new, npos, N + sidx)
    nk_words = jnp.zeros((N, K - 1), jnp.int32) \
        .at[tgt].set(words, mode="drop", unique_indices=True)
    nk_sidx = jnp.zeros((N,), jnp.int32) \
        .at[tgt].set(sidx_s, mode="drop", unique_indices=True)
    nk_lb = jnp.zeros((N,), jnp.int32) \
        .at[tgt].set(lb, mode="drop", unique_indices=True)
    nvalid = sidx < new_count

    # rank merge into seen2: pos(new j) = lb_seen + j,
    # pos(seen i) = i + ranks(i) — a bijection since new keys are
    # distinct from seen keys.  ranks[i] = #{valid new j : key_j <
    # seen[i]} needs NO second binary search: key_j < seen[i] iff its
    # lower bound nk_lb[j] <= i, so a scatter-add histogram of the
    # nk_lb values + one inclusive cumsum gives every seen row's
    # shift in O(SC + N) cheap ops (the SC-query binary search this
    # replaces measurably dominated the mesh merge wall, ISSUE 10)
    hist = jnp.zeros((SC + 1,), jnp.int32)
    hist = hist.at[jnp.where(nvalid, jnp.clip(nk_lb, 0, SC), SC)] \
        .add(1)
    ranks = jnp.cumsum(hist[:SC])
    valid_seen_rows = jnp.arange(SC) < seen_count
    # dropped (invalid) rows get DISTINCT out-of-range indices
    # (SC + arange): unique_indices=True is a correctness promise to
    # XLA, and funnelling every invalid row to the same index would be
    # documented UB even though mode="drop" discards the writes
    # (advisor r2)
    pos_s = jnp.where(valid_seen_rows,
                      jnp.arange(SC, dtype=jnp.int32) + ranks,
                      SC + jnp.arange(SC, dtype=jnp.int32))
    seen2 = jnp.full((SC, K), SENTINEL, jnp.int32)
    seen2 = seen2.at[:, 0].set(1)  # invalid tail: validity lane 1
    seen2 = seen2.at[pos_s].set(seen, mode="drop",
                                unique_indices=True)
    nk_full = jnp.concatenate(
        [jnp.zeros((N, 1), jnp.int32), nk_words], axis=1)
    pos_n = jnp.where(nvalid, nk_lb + sidx, SC + sidx)
    seen2 = seen2.at[pos_n].set(nk_full, mode="drop",
                                unique_indices=True)
    return dict(new_count=new_count, nk_sidx=nk_sidx, seen2=seen2,
                seen_count2=seen_count + new_count)


class _LiveGraph:
    """Host-side behavior-graph accumulator for device runs.

    Mirrors the interp engine's bookkeeping (engine/explore.py): kept
    states get dense ids in discovery order; edges record every
    (parent, kept-successor) step including re-visits of already-seen
    states; parents/labels form the BFS tree for trace reconstruction.
    Constraint-discarded successors never enter the graph — the same
    mask that keeps them off the device frontier keeps them out here."""

    def __init__(self, labels_flat: List[str], collect_edges: bool):
        self.labels_flat = labels_flat
        self.collect_edges = collect_edges
        self.rows: List[np.ndarray] = []
        self.sid_by_key: Dict[bytes, int] = {}
        self.parents: List[Optional[int]] = []
        self.labels: List[str] = []
        self.edges: List[Tuple[int, int]] = []

    def add_inits(self, init_rows, explored_idx) -> np.ndarray:
        sids = []
        for i in explored_idx:
            row = np.array(init_rows[i], copy=True)
            sid = len(self.rows)
            self.rows.append(row)
            self.sid_by_key[row.tobytes()] = sid
            self.parents.append(None)
            self.labels.append("Initial predicate")
            sids.append(sid)
        return np.asarray(sids, dtype=np.int64)

    def add_level(self, new_rows, new_prov, par_div: int,
                  frontier_sids: np.ndarray) -> np.ndarray:
        """Register this level's kept rows; prov = action*par_div + f."""
        sids = []
        for i in range(len(new_rows)):
            row = np.array(new_rows[i], copy=True)
            sid = len(self.rows)
            self.rows.append(row)
            self.sid_by_key[row.tobytes()] = sid
            p = int(new_prov[i])
            a, f = p // par_div, p % par_div
            self.parents.append(int(frontier_sids[f]))
            self.labels.append(self.labels_flat[a])
            sids.append(sid)
        return np.asarray(sids, dtype=np.int64)

    def add_edges(self, rows: np.ndarray, parent_f: np.ndarray,
                  frontier_sids: np.ndarray) -> None:
        """Record edges (frontier_sids[parent_f[i]] -> sid of rows[i]) for
        pre-masked kept candidates; call after add_level so same-level
        successors resolve."""
        if not self.collect_edges:
            return
        for i in range(len(rows)):
            t = self.sid_by_key.get(rows[i].tobytes())
            if t is None:
                continue  # fp-collision shadow; counts already report it
            self.edges.append(
                (int(frontier_sids[int(parent_f[i])]), t))


class TpuExplorer:
    def __init__(self, model: Model, log: Callable[[str], None] = None,
                 max_states: Optional[int] = None, store_trace: bool = True,
                 progress_every: float = 30.0,
                 bounds: Optional[Bounds] = None,
                 sample_cfg: Tuple[int, int, int] = (800, 40, 60),
                 host_seen: bool = False, chunk: int = 2048,
                 resident: bool = False,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every: float = 600.0,
                 resume_from: Optional[str] = None,
                 extra_samples: Optional[List[Dict[str, Any]]] = None,
                 relayouts_left: int = 3,
                 pin_interp_arms: bool = False,
                 res_caps: Optional[Dict[str, int]] = None,
                 cap_profile: bool = True,
                 final_checkpoint: bool = False,
                 backend: Optional["BackendDescriptor"] = None,
                 seen_mode: str = "auto",
                 seen_cap: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 host_tier_keys: Optional[int] = None,
                 lift_consts: Optional[Tuple[str, ...]] = None,
                 por: bool = False,
                 donor: Optional["TpuExplorer"] = None):
        # cross-model batching (ISSUE 13): `lift_consts` compiles the
        # named CONSTANTs as traced kernel inputs instead of baked
        # scalars, so one compiled program serves every model that
        # differs only in those values; `donor` clones a FOLLOWER
        # engine that reuses the donor's layout + compiled kernels
        # (zero kernel builds) while keeping its own model, init
        # states, seen store and checkpoint surface.
        self._hstep_override: Optional[Callable] = None
        # device POR (ISSUE 18): the persistent-set filter runs INSIDE
        # the fused step (level/resident/host_seen), reusing the seen
        # probe the merge performs anyway — the plan (instance->arm map
        # + por-safe mask) is resolved lazily by _por_plan(), which
        # names the refusal when the reduction cannot run
        self.por = bool(por)
        self.por_reason: Optional[str] = None
        self._por_memo: Any = _POR_UNSET
        self._por_stats = {"ample": 0, "expanded": 0, "masked": 0}
        if donor is not None:
            self._clone_from_donor(
                donor, model, log=log, max_states=max_states,
                store_trace=store_trace, progress_every=progress_every,
                checkpoint_path=checkpoint_path,
                checkpoint_every=checkpoint_every,
                resume_from=resume_from,
                final_checkpoint=final_checkpoint)
            return
        self._lift_names: Tuple[str, ...] = tuple(lift_consts or ())
        if self._lift_names and not host_seen:
            raise ModeError(
                "lifted-constant (batchable) engines run in host_seen "
                "mode only — the level/resident/mesh steps do not "
                "thread constant lanes")
        self.model = model
        # the device layer this engine is compiled FOR (ISSUE 11): one
        # descriptor instead of per-engine re-derivation from global
        # jax state — platform, donation policy and the capacity-
        # profile namespace all read from it, so caps learned on one
        # platform can never warm-start another
        from . import describe_backend
        self.backend_desc = backend if backend is not None \
            else describe_backend()
        # persist a checkpoint when the search COMPLETES (not just on
        # truncation): the serve daemon's warm-resume source — an
        # identical later job resumes it, replays the stored totals
        # over an empty frontier, and finishes in one dispatch
        self.final_checkpoint = final_checkpoint
        # same funnel as cli.py: silent on stdout by default, but the
        # strings still mirror into the telemetry trace
        self.log = log if log is not None else obs.Logger(quiet=True)
        self.max_states = max_states
        self.store_trace = store_trace
        self.progress_every = progress_every
        self.bounds = bounds or Bounds()
        self.host_seen = host_seen
        self.chunk = chunk
        self.resident = resident
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = checkpoint_every
        self.resume_from = resume_from
        self.sample_cfg = sample_cfg
        # ADAPTIVE RELAYOUT (hybrid, host_seen): when a compile-recovery
        # demotion fires because a value SHAPE was never observed by the
        # layout sampler (a deep model's rare message variant), the
        # engine re-samples from the abort-time frontier, rebuilds the
        # layout and kernels with the enriched observation set, and
        # restarts COMPILED — falling back to whole-arm interpretation
        # only after relayouts_left attempts.
        self.extra_samples = list(extra_samples or [])
        self.relayouts_left = relayouts_left
        # expansion-mode pin (ISSUE 5): the corpus manifest knows this
        # model's arms ALL demote to the interpreter — skip grounding +
        # kernel construction + forced tracing entirely instead of
        # paying minutes of futile XLA work (MCInnerSerial burned 213s
        # building 13 kernels it then demoted, SWEEP_JAX_r05).
        self.pin_interp_arms = pin_interp_arms
        self._res_caps_hint = dict(res_caps) if res_caps else None
        self.cap_profile = cap_profile
        self._last_frontier_np: Optional[np.ndarray] = None

        tel = obs.current()
        base_ctx = model.ctx()
        self.init_states = enumerate_init(model.init, base_ctx, model.vars)
        bfs_n, walks, depth = sample_cfg
        with tel.span("layout_sample", bfs_states=bfs_n, walks=walks,
                      walk_depth=depth):
            sampled = sample_states(model, bfs_states=bfs_n,
                                    n_walks=walks, walk_depth=depth)
        sampled = list(sampled) + self.extra_samples
        # static bounds inference (ISSUE 9): a converged interval proof
        # turns observed-range guarded int lanes into proven-width lanes
        # — the OV_PACK re-sample cycle cannot fire on a proven lane.
        # The fixpoint result is cached on the model so relayout
        # restarts and mesh subclasses do not re-run it.
        self._static_bounds = None
        from .. import analyze as _analyze
        if _analyze.bounds_enabled():
            rep = getattr(model, "_bounds_report", _SENTINEL_NO_REPORT)
            if rep is _SENTINEL_NO_REPORT:
                with tel.span("analyze_bounds"):
                    rep = _analyze.infer_state_bounds(model)
                try:
                    model._bounds_report = rep
                except AttributeError:
                    pass
            if rep is not None:
                # per-element structured bounds when the report carries
                # them (ISSUE 15: container element lanes pack at their
                # own proven widths); merged/shim reports (batch donor
                # builds) still provide whole-variable intervals
                ebf = getattr(rep, "element_bounds", None)
                self._static_bounds = ebf() if callable(ebf) \
                    else rep.lane_bounds()
                tel.gauge("analyze.bounds_converged",
                          bool(rep.converged))
        with tel.span("layout_build", samples=len(sampled)):
            self.layout = build_layout2(model, sampled, self.bounds,
                                        static_bounds=self._static_bounds)
        self.kc = KernelCtx(model, self.layout, self.bounds)
        # per-model lifted-constant values, in _lift_names order: the
        # runtime input vector the shared kernels read instead of baked
        # scalars (empty for ordinary engines — same code path)
        self._cvec = np.asarray([int(model.defs[n])
                                 for n in self._lift_names], np.int32)
        # dynamic \E expansion applies to message tables AND to
        # state-dependent intervals (\E i \in 1..Len(q), AlternatingBit's
        # Lose); slots beyond the actual element count are mask-disabled.
        #
        # Hybrid execution (VERDICT r3 #2): Next splits into disjunct
        # arms; an arm whose grounding or kernel compilation fails is
        # demoted to exact interpreter enumeration over decoded frontier
        # states (host_seen mode only) instead of rejecting the spec.
        # Kernel CompileErrors surface lazily at jit-trace time, so each
        # compiled unit is force-traced here with jax.eval_shape
        # (abstract evaluation — no XLA compile cost).
        row_spec = jax.ShapeDtypeStruct((self.layout.width,), jnp.int32)
        slot_spec = jax.ShapeDtypeStruct((), jnp.int32)
        self.arms = split_arms(model)
        self.actions = []
        self.compiled = []
        self._ca_arm: List[int] = []  # arm index per compiled action
        self.fb_arms: List[Tuple[Any, str]] = []  # (ActionArm, reason)
        # per-arm compile introspection (ISSUE 2): jaxpr equation count
        # and HLO flops/bytes per kernel, aggregated per arm label. The
        # introspection trace replaces the eval_shape forced trace, so
        # the only extra cost vs an untelemetered build is the lowering
        # for cost_analysis (JAXMC_COMPILE_INTROSPECT=0 skips it).
        arm_costs: Dict[str, Dict[str, int]] = {}
        zero_row = jnp.zeros((self.layout.width,), jnp.int32)
        zero_slot = jnp.zeros((), jnp.int32)
        # transient per-arm compile failures (a flaky device link mid-
        # lowering, injected compile_fail faults) get a bounded retry
        # with backoff before the failure escapes to cli.py's demotion
        # path; REAL CompileErrors are deterministic and still demote
        # the arm to the interpreter immediately, as before
        compile_retries = int(os.environ.get("JAXMC_COMPILE_RETRIES",
                                             "2"))
        from .. import faults as _faults
        if self.pin_interp_arms:
            self.fb_arms = [(arm, "pinned interp-arms (corpus "
                                  "manifest): kernel construction "
                                  "skipped") for arm in self.arms]
        # statically-predicted demotions (ISSUE 9): arms the analyzer is
        # CERTAIN compile_action2 would demote skip grounding + kernel
        # construction + forced tracing outright — the derived
        # generalization of the manifest's measured pin_interp_arms
        # pins.  The verdict string IS the build-time reason string
        # (kernel2's shared message constants), so the demotion table,
        # the ModeError text and the sweep notes read identically on
        # either path.
        self.arm_verdicts: Dict[int, str] = {}
        if not self.pin_interp_arms and _analyze.predict_enabled() \
                and self.arms:
            with tel.span("analyze_arms", arms=len(self.arms)):
                self.arm_verdicts = _analyze.predict_arm_demotions(
                    model, self.arms)
            if self.arm_verdicts:
                tel.counter("analyze.predicted_demotions",
                            len(self.arm_verdicts))
                tel.gauge("analyze.arm_verdicts",
                          {(self.arms[i].label or "Next"): r
                           for i, r in sorted(self.arm_verdicts.items())})
        for ai, arm in enumerate(
                () if self.pin_interp_arms else self.arms):
            if ai in self.arm_verdicts:
                # zero futile build attempts: the arm goes straight to
                # the interpreter with the predicted (== build-time)
                # reason
                self.fb_arms.append((arm, self.arm_verdicts[ai]))
                continue
            try:
                for attempt in range(compile_retries + 1):
                    # per-ATTEMPT introspection buffer: the rollup
                    # (arm_costs + the *_total counters) commits only
                    # when the attempt succeeds, so a retried arm never
                    # double-counts the kernels introspected before the
                    # transient failure (the per-attempt span still
                    # carries its own attrs — that is honest span data)
                    att_costs: Dict[str, int] = {}
                    try:
                        # the span covers grounding + kernel build + the
                        # forced abstract trace — the per-arm compile
                        # cost the bench forensics need (BENCH_r05:
                        # nothing said whether compile or BFS ate the
                        # deadline)
                        with tel.span("compile_arm",
                                      arm=arm.label or "Next") as asp:
                            _faults.inject("compile_fail",
                                           arm=arm.label or "Next")
                            gas = ground_arm(model, arm,
                                             dyn_slots=self.bounds.kv_cap)
                            cas = []
                            for ga in gas:
                                ca = compile_action2(self.kc, ga)
                                if self._lift_names:
                                    # lifted build: the forced abstract
                                    # trace installs const TRACERS so
                                    # compile success/demotion is
                                    # decided exactly as the shared
                                    # run-time trace will decide it
                                    # (introspection skipped — it would
                                    # re-trace without the lanes)
                                    cspec = jax.ShapeDtypeStruct(
                                        (len(self._lift_names),),
                                        jnp.int32)
                                    if ca.n_slots:
                                        jax.eval_shape(
                                            partial(self._traced_with,
                                                    ca.fn),
                                            cspec, row_spec, slot_spec)
                                    else:
                                        jax.eval_shape(
                                            partial(self._traced_with,
                                                    ca.fn),
                                            cspec, row_spec)
                                    cas.append(ca)
                                    continue
                                if tel.enabled:
                                    # the introspection trace IS the
                                    # forced abstract trace (same lazy
                                    # CompileError/RecursionError
                                    # surface as eval_shape) — one
                                    # trace per kernel either way
                                    info = introspect_kernel(
                                        ca.fn, (zero_row, zero_slot)
                                        if ca.n_slots else (zero_row,))
                                    for k, v in info.items():
                                        att_costs[k] = \
                                            att_costs.get(k, 0) + v
                                        asp.attrs[k] = \
                                            asp.attrs.get(k, 0) + v
                                elif ca.n_slots:
                                    jax.eval_shape(ca.fn, row_spec,
                                                   slot_spec)
                                else:
                                    jax.eval_shape(ca.fn, row_spec)
                                cas.append(ca)
                        if att_costs:
                            acc = arm_costs.setdefault(
                                arm.label or "Next", {})
                            for k, v in att_costs.items():
                                acc[k] = acc.get(k, 0) + v
                                tel.counter(
                                    {"jaxpr_eqns":
                                     "compile.jaxpr_eqns_total",
                                     "hlo_flops":
                                     "compile.hlo_flops_total",
                                     "hlo_bytes":
                                     "compile.hlo_bytes_total"}[k], v)
                        break
                    except RecursionError:
                        raise  # deterministic (RuntimeError subclass)
                    except (_faults.FaultInjected, OSError,
                            RuntimeError) as ex:
                        if attempt >= compile_retries:
                            raise
                        tel.counter("compile.retries")
                        self.log(f"-- compile_arm "
                                 f"{arm.label or 'Next'}: transient "
                                 f"failure ({ex}); retrying "
                                 f"({attempt + 1}/{compile_retries})")
                        time.sleep(min(0.1 * (2 ** attempt), 2.0))
            except CompileError as e:
                self.fb_arms.append((arm, str(e)))
                continue
            except RecursionError:
                # a RECURSIVE operator with symbolic arguments unrolls
                # forever at trace time — demote the arm like any other
                # uncompilable construct instead of crashing the build
                self.fb_arms.append(
                    (arm, "recursive operator expansion diverges at "
                          "compile time (RecursionError)"))
                continue
            self.actions.extend(gas)
            self.compiled.extend(cas)
            self._ca_arm.extend([ai] * len(cas))
        if arm_costs:
            # machine-readable per-arm compile-cost map (schema v2):
            # {arm label -> {jaxpr_eqns, hlo_flops?, hlo_bytes?}}
            tel.gauge("compile.arm_cost", arm_costs)
        # per-arm demotion reasons (ISSUE 5 / VERDICT r5 #4): the sweep
        # log used to say only "13 arms interp-demoted" — name each arm
        # and WHY, so a mechanical arm wrongly demoted (vs a genuinely
        # recursive one) is visible instead of folded into a count
        for _arm, _reason in self.fb_arms:
            self.log(f"-- arm {_arm.label or 'Next'}: interp-demoted "
                     f"({_reason})")
        # kernels that compiled only by DEMOTING a guard conjunct (False
        # + abort flag) under-approximate behind a runtime abort. Most
        # demotions never fire (raft's Receive reads fields of message
        # variants that never occur under the micro constraints); when
        # one DOES fire, the host_seen engine demotes those arms to the
        # interpreter and restarts the search (see run()) instead of
        # reporting a spurious capacity overflow.
        self._demotable = sorted({self._ca_arm[i]
                                  for i, ca in enumerate(self.compiled)
                                  if ca.demoted_guards})
        # flat instance list: slotted kernels contribute n_slots rows
        self.labels_flat = []
        for ca in self.compiled:
            if ca.n_slots:
                self.labels_flat.extend(
                    [ca.label] * ca.n_slots)
            else:
                self.labels_flat.append(ca.label)
        # cfg SYMMETRY: canonicalize rows to their orbit representative
        # before fingerprinting (same partition, hence same counts, as
        # the interp's make_canonicalizer); encodings the transform
        # builder rejects fall back to the unreduced search with the
        # SYMMETRY warning
        self.canon_fn = None
        self._sym_fallback: Optional[str] = None
        if model.symmetry is not None:
            from ..compile.symmetry2 import build_canon2
            try:
                self.canon_fn = build_canon2(model, self.layout)
            except CompileError as e:
                self._sym_fallback = str(e)
        # identity-group disclosure (ISSUE 5 satellite): build_canon2
        # returns None BY DESIGN when every declared permutation is the
        # identity — no reduction exists to diverge from, so no
        # UNREDUCED-FALLBACK warning belongs here (the interp's
        # make_canonicalizer returns None for the same group, so counts
        # match TLC exactly). Only a genuine CompileError fallback
        # (self._sym_fallback) reports divergence.
        self.sym_identity = (model.symmetry is not None
                             and self.canon_fn is None
                             and self._sym_fallback is None)
        # predicates likewise force-traced; uncompilable ones demote to
        # host-side interpreter evaluation over decoded rows (hybrid).
        # A TRACE-TIME BUDGET (JAXMC_PRED_TRACE_BUDGET seconds, default
        # 15) also demotes predicates whose symbolic programs explode —
        # MCVoting's inductive Inv unrolls its quantifier towers into a
        # ~50k-op jaxpr whose XLA:CPU compile alone blew the r3 sweep's
        # 900 s case timeout; the exact interpreter checks such
        # predicates on new rows at negligible cost instead.
        budget = float(os.environ.get("JAXMC_PRED_TRACE_BUDGET", "15"))

        def _compile_preds(pairs, may_demote_on_budget):
            """(compiled, demoted) for a predicate list. Uncompilable
            predicates always demote (hybrid checks them exactly); a
            predicate whose abstract trace exceeds the budget demotes
            only when may_demote_on_budget — callers keep slow compiled
            predicates when demotion would make the run unsupported
            (non-host_seen modes; constraints under temporal/refinement
            PROPERTYs), so a loaded box never REFUSES a spec an idle
            box accepts."""
            compiled, demoted = [], []
            for nm, ex in pairs:
                f = compile_predicate2(self.kc, ex)
                t_tr = time.time()
                try:
                    if self._lift_names:
                        jax.eval_shape(
                            partial(self._traced_with, f),
                            jax.ShapeDtypeStruct(
                                (len(self._lift_names),), jnp.int32),
                            row_spec)
                    else:
                        jax.eval_shape(f, row_spec)
                except CompileError as e:
                    demoted.append((nm, ex, str(e)))
                    continue
                except RecursionError:
                    demoted.append(
                        (nm, ex, "recursive operator expansion diverges "
                                 "at compile time (RecursionError)"))
                    continue
                t_tr = time.time() - t_tr
                if t_tr > budget and may_demote_on_budget:
                    demoted.append(
                        (nm, ex,
                         f"trace budget exceeded ({t_tr:.0f}s > "
                         f"{budget:.0f}s [JAXMC_PRED_TRACE_BUDGET]; the "
                         f"compiled program would dwarf the model)"))
                    continue
                compiled.append((nm, f))
            return compiled, demoted

        with tel.span("compile_predicates",
                      invariants=len(model.invariants),
                      constraints=len(model.constraints)):
            self.inv_fns, self.fb_invs = _compile_preds(
                model.invariants, host_seen)
            self.constraint_fns, self.fb_cons = _compile_preds(
                model.constraints, host_seen and not model.properties)
        if model.action_constraints:
            raise CompileError("action constraints not compiled yet - "
                               "use the interp backend")
        # cfg VIEW (ISSUE 6): compile V to its value lanes and key the
        # dedup on them — TLC fingerprints the view, not the state
        # (ConfigFileGrammar.tla:8-11); the kept rows stay full states
        # so traces/decodes are unchanged.  An uncompilable view still
        # refuses the spec (the interp backend remains its checker).
        self.view_fn = None
        self.view_width = 0
        if getattr(model, "view", None) is not None:
            try:
                self.view_fn = compile_value2(self.kc, model.view)
                vsh = jax.eval_shape(self.view_fn, row_spec)
                self.view_width = int(np.prod(vsh.shape)) \
                    if vsh.shape else 1
            except RecursionError:
                raise CompileError(
                    "cfg VIEW expression recurses unboundedly at compile "
                    "time - use --backend interp")
            if self.view_width == 0:
                raise CompileError(
                    "cfg VIEW evaluates to zero lanes - use --backend "
                    "interp")
        # refinement PROPERTYs check stepwise on the host over the
        # streamed candidate edges — same verdicts as the interp backend
        from ..engine.refinement import build_refinement_checkers
        self.refiners, self.unrefined = build_refinement_checkers(model)
        self._ref_pair_cache: set = set()
        # temporal (liveness) obligations check over the behavior graph
        # after the search completes, exactly like the interp backend:
        # kept states/edges stream to the host during the run and feed
        # engine/liveness.py — same classifier, same checker, same verdict
        from ..engine.liveness import collect_obligations
        self.live_obligations, self.live_unsupported, self.collect_edges = \
            collect_obligations(model, self.refiners)
        self.hybrid = bool(self.fb_arms or self.fb_invs or self.fb_cons)
        if self.hybrid:
            reasons = "; ".join(
                [f"action arm {a.label or 'Next'}: {r}"
                   for a, r in self.fb_arms]
                + [f"invariant {nm}: {r}" for nm, _, r in self.fb_invs]
                + [f"constraint {nm}: {r}" for nm, _, r in self.fb_cons])
            if not host_seen:
                raise ModeError(
                    "spec needs hybrid execution (uncompilable units "
                    "demoted to the exact interpreter), which only the "
                    "host_seen device mode runs — pass host_seen=True; "
                    f"demoted units: {reasons}")
            if self.fb_cons and (self.collect_edges or self.refiners):
                raise CompileError(
                    "uncompilable CONSTRAINT together with temporal/"
                    "refinement PROPERTYs is not supported on the device "
                    f"backend — use --backend interp; units: {reasons}")
            if not self.compiled and self.fb_arms:
                self.log("hybrid: EVERY action arm fell back to the "
                         "interpreter — the device does hashing/dedup "
                         "only on this model")
        # device flat-instance count; fallback arm j takes provenance
        # index A + j so traces and the behavior graph resolve labels
        # through one table
        self.A = len(self.labels_flat)
        self.labels_flat = self.labels_flat + \
            [arm.label or "Next" for arm, _ in self.fb_arms]
        self.W = self.layout.width
        # ENGINE storage format (ISSUE 6): rows cross the kernel/engine
        # boundary BIT-PACKED (compile/pack.py) — the frontier, the seen
        # table, trace levels, checkpoints and the candidate streams all
        # hold [*, PW] packed rows; kernels unpack to [*, W] lanes at
        # the top of each jitted step.  The exact-dedup/fp128 threshold
        # is recomputed over the PACKED width (or the view width when
        # cfg VIEW keys the dedup).
        self.PW = self.layout.packed_width
        self.plan = self.layout.plan
        self.key_width = self.view_width if self.view_fn is not None \
            else self.PW
        self.fp_mode = self.key_width > FP_THRESHOLD
        # expansion-mode disclosure, machine-readable (mirrors the sweep's
        # per-case note): gauges overwrite on relayout restarts so the
        # artifact reports the engine that actually ran
        tel.gauge("expand.arms_total", len(self.arms))
        tel.gauge("expand.arms_compiled",
                  len(self.arms) - len(self.fb_arms))
        tel.gauge("expand.arms_interp", len(self.fb_arms))
        tel.gauge("expand.compiled_instances", self.A)
        tel.gauge("expand.invariants_interp", len(self.fb_invs))
        tel.gauge("expand.constraints_interp", len(self.fb_cons))
        tel.gauge("expand.mode",
                  "compiled" if not self.fb_arms
                  else ("hybrid" if self.A else "interp-arms"))
        tel.gauge("layout.width_lanes", self.W)
        tel.gauge("layout.packed_width_lanes", self.PW)
        # dedup key lanes: an explicit validity lane FIRST (0=valid row,
        # 1=invalid) — validity must never be encoded in-band in hash
        # output or state lanes, either could legitimately equal SENTINEL
        self.K = (4 if self.fp_mode else self.key_width) + 1
        tel.gauge("dedup.mode",
                  ("fp128" if self.fp_mode else "exact")
                  + ("-view" if self.view_fn is not None
                     else ("-packed" if not self.plan.identity else "")))
        # buffer donation (ISSUE 6): donate the seen table and frontier
        # into the jitted steps so XLA updates them in place instead of
        # allocating a copy per level.  XLA:CPU ignores donation (with a
        # warning), so it defaults on only for accelerator backends;
        # JAXMC_DONATE=1/0 forces it either way — the policy lives on
        # the backend descriptor since ISSUE 11.
        self.donate = bool(self.backend_desc.donate)
        tel.gauge("device.donation", bool(self.donate))
        tel.gauge("backend.platform", self.backend_desc.platform)
        tel.gauge("backend.profile_ns", self.backend_desc.profile_ns)
        self._trace_lock = threading.Lock()
        self._step_cache: Dict[Tuple[int, int], Callable] = {}
        self._hstep_cache: Dict[int, Callable] = {}
        self._hstep_group_jits: Dict[
            int, Tuple[List[Callable], List[np.ndarray]]] = {}
        self._newcheck_cache: Dict[int, Callable] = {}
        self._res_cache: Dict[Tuple[int, ...], Callable] = {}
        self._hostkeys_cache: Dict[int, Callable] = {}
        self._pkeys_cache: Dict[int, Callable] = {}
        # capacities learned by previous resident runs on this instance:
        # a warm-up run trains them so the timed run never overflows
        # (and therefore never recompiles)
        self._res_caps: Optional[Dict[str, int]] = None
        self._res_maxlvl = 64  # levels per resident dispatch
        if resident:
            if host_seen:
                raise ModeError(
                    "resident and host_seen are mutually exclusive: "
                    "resident keeps the seen-set on device, host_seen "
                    "keeps it in the native host store")
            if self.refiners:
                raise ModeError(
                    "resident mode cannot check refinement PROPERTYs "
                    "(stepwise host checking needs the edge stream) - "
                    "use the level/host_seen device modes")
            if self.live_obligations:
                raise ModeError(
                    "resident mode cannot check temporal properties "
                    "(the behavior graph stays on device) - use the "
                    "level/host_seen device modes")
            self.store_trace = False
            # resident dedup keys are always 128-bit fingerprints: the
            # rank-merge binary search and the LSD key sorts are built
            # for a fixed 4-word key
            if not self.fp_mode:
                self.fp_mode = True
                self.K = 4 + 1
        if host_seen:
            from .. import native_store
            if not native_store.is_available():
                raise CompileError(f"host_seen requires the native store: "
                                   f"{native_store.build_error()}")
            if not self.fp_mode:
                # narrow layouts also hash fine; host store is fp-based
                self.fp_mode = True
                self.K = 4 + 1
        # EXPLICIT seen-key mode (ISSUE 12): --seen fingerprint trades
        # exact dedup keys for 128-bit fingerprints on ANY layout (the
        # machinery that always kicked in past FP_THRESHOLD), shrinking
        # the per-state tier footprint (K+1 -> 5 words) by the
        # key-width ratio; the collision-probability bound rides the
        # result.  --seen exact REFUSES configurations that cannot
        # honor it instead of silently fingerprinting.
        if seen_mode not in ("auto", "exact", "fingerprint"):
            raise ModeError(f"unknown --seen mode {seen_mode!r} "
                            f"(expected auto, exact or fingerprint)")
        self.seen_mode_req = seen_mode
        if seen_mode == "fingerprint" and not self.fp_mode:
            self.fp_mode = True
            self.K = 4 + 1
        elif seen_mode == "exact" and self.fp_mode:
            if resident or host_seen:
                raise ModeError(
                    "--seen exact is incompatible with the resident/"
                    "host_seen modes (their dedup machinery is "
                    "fingerprint-based) — use the level device mode")
            raise ModeError(
                f"--seen exact refused: the dedup key is "
                f"{self.key_width} lanes wide (> FP_THRESHOLD="
                f"{FP_THRESHOLD}); exact keys at this width would "
                f"dominate device memory — use --seen fingerprint "
                f"(collision probability is reported) or --backend "
                f"interp")
        # re-stamp after the resident/host_seen fp forcings so the
        # artifact records the dedup mode that actually runs
        tel.gauge("dedup.mode",
                  ("fp128" if self.fp_mode else "exact")
                  + ("-view" if self.view_fn is not None
                     else ("-packed" if not self.plan.identity else "")))
        tel.gauge("seen.mode",
                  "fingerprint" if self.fp_mode else "exact")
        # HIERARCHICAL SEEN SET (ISSUE 12 tentpole): a device seen cap
        # (rows of the key table; --seen-cap, JAXMC_SEEN_CAP is the
        # test knob) turns would-be unbounded device growth into tier
        # SPILL — the sorted device prefix compacts out to host RAM and
        # then disk as immutable sorted runs (backend/tiers.py), and
        # per-level survivors of the device rank-merge binary-search
        # the cold runs before they are counted or explored.  Counts
        # and traces stay bit-identical to the uncapped run.  None =
        # today's grow-forever behavior (no cap, no tiers).
        env_cap = os.environ.get("JAXMC_SEEN_CAP")
        self.seen_cap = int(seen_cap if seen_cap is not None
                            else (env_cap if env_cap else 0)) or None
        if self.seen_cap is not None:
            self.seen_cap = _pow2_at_least(self.seen_cap, lo=64)
            tel.gauge("tier.device_cap", self.seen_cap)
        self.spill_dir = spill_dir or os.environ.get("JAXMC_SPILL_DIR")
        self.host_tier_keys = host_tier_keys
        self._tiers = None  # created lazily at the first spill
        # LEARNED CAPACITY PROFILE (ISSUE 6): resident runs start at the
        # caps a previous completed run on this (module, layout) ended
        # with — persisted next to the compile cache — so the one
        # warm-up compile covers the whole run and window_recompiles
        # reads 0 on a second run.  Max-merged with any caller hint
        # (bench manifest caps); a stale/foreign profile is ignored with
        # a named profile.status reason (cache.load_capacity_profile).
        if resident and self.cap_profile:
            from ..compile.cache import load_capacity_profile
            prof = load_capacity_profile(
                model.module.name, self._layout_sig(), tel=tel,
                variant=self.backend_desc.profile_variant(),
                optional=("TIERK",))
            if not prof and not self._res_caps_hint:
                # PREDICTED capacity rung (ISSUE 15, below `learned`):
                # a converged bounds fixpoint proves a state-count
                # ceiling, so a COLD first-contact run can size every
                # bucket up front instead of paying growth-retry
                # recompile doublings — window_recompiles reads 0 on
                # fully-proven specs with no saved profile
                pred = self._predicted_caps()
                if pred:
                    self._res_caps_hint = pred
            if prof:
                hint = dict(self._res_caps_hint or {})
                for kk, vv in prof.items():
                    hint[kk] = max(int(hint.get(kk, 0)), vv)
                self._res_caps_hint = hint
                if prof.get("TIERK") and self.seen_cap is not None:
                    # learned tier size (ISSUE 12): a previous
                    # completed run on this (module, layout, platform)
                    # spilled ~TIERK keys — surface the expected
                    # out-of-core magnitude up front so operators and
                    # bench artifacts see it before the first spill
                    tel.gauge("tier.predicted_keys",
                              int(prof["TIERK"]))
                    self.log(f"-- tier: capacity profile predicts an "
                             f"out-of-core run (~{int(prof['TIERK'])} "
                             f"cold-tier keys at the last completion)")

    # ---- predicted capacities (ISSUE 15 tentpole c) -------------------

    def state_estimate(self) -> Optional[int]:
        """analyze's proven state-count ceiling for this model, or None
        (fixpoint bailed / some variable unbounded)."""
        from ..analyze.bounds import BoundsReport, state_space_estimate
        rep = getattr(self.model, "_bounds_report", None)
        if not isinstance(rep, BoundsReport) or not rep.converged:
            return None
        try:
            return state_space_estimate(self.model, rep)
        except Exception:
            if os.environ.get("JAXMC_DEBUG"):
                raise
            return None

    def _predicted_caps(self) -> Optional[Dict[str, int]]:
        """Bounds-sized initial buckets for a cold resident run: the
        capacity-profile ladder's `predicted` rung (below `learned`,
        above the platform defaults).  Only fires when the proven state
        count is small enough that over-allocation is cheap
        (JAXMC_PREDICT_MAX, default 1<<18 states) — a wrong refusal
        costs growth recompiles exactly as before, never memory."""
        est = self.state_estimate()
        cap_max = int(os.environ.get("JAXMC_PREDICT_MAX",
                                     str(1 << 18)))
        if not est or est > cap_max:
            return None
        tel = obs.current()
        caps = {"SC": _pow2_at_least(4 * est, lo=256),
                "FCap": _pow2_at_least(est, lo=64),
                "AccCap": _pow2_at_least(2 * est, lo=128),
                "VC": _pow2_at_least(4 * est, lo=64)}
        tel.gauge("profile.status", "predicted")
        tel.gauge("profile.predicted_states", int(est))
        tel.gauge("profile.predicted_caps", dict(caps))
        self.log(f"-- capacity profile: predicted rung — analyze "
                 f"proves <= {est} states; buckets sized up front "
                 f"(no growth-retry recompiles expected)")
        return caps

    # ---- device persistent-set reduction (ISSUE 18) -------------------

    def _por_plan(self) -> Optional[Dict[str, np.ndarray]]:
        """The device POR plan, or None with the named refusal in
        self.por_reason (the engine then runs UNREDUCED and discloses
        why — same surface as the interp backend's por_refusal path).

        plan = dict(inst_arm [A] int32 — split-arm index per flat
        kernel instance (slotted kernels contribute n_slots entries),
        arm_safe [n_arms] bool — arms the independence report proved
        commuting-with-all + property-invisible).  Memoized: the
        independence analysis walks the AST once per engine."""
        if self._por_memo is not _POR_UNSET:
            return self._por_memo
        from ..analyze.independence import (indep_enabled,
                                            independence_report,
                                            por_refusal)
        plan = None
        reason = None
        if not self.por:
            reason = "POR not requested"
        elif not indep_enabled():
            reason = ("independence analysis disabled "
                      "(JAXMC_ANALYZE_INDEP=0)")
        elif self.hybrid:
            reason = ("hybrid execution: interp-demoted units expand "
                      "on the host where the device mask cannot reach "
                      "them")
        else:
            reason = por_refusal(self.model)
            if reason is None and (self.canon_fn is not None
                                   or self.sym_identity):
                reason = "symmetry canonicalizer active"
            if reason is None:
                try:
                    irep = independence_report(self.model, self.arms)
                except Exception:
                    if os.environ.get("JAXMC_DEBUG"):
                        raise
                    irep = None
                if irep is None:
                    reason = "independence analysis failed"
                elif not irep.por_safe:
                    reason = ("no arm commutes with every other arm "
                              "invisibly")
                else:
                    safe = np.zeros(len(self.arms), dtype=bool)
                    safe[list(irep.por_safe)] = True
                    inst = np.asarray(
                        [self._ca_arm[ci]
                         for ci, ca in enumerate(self.compiled)
                         for _ in range(max(1, ca.n_slots))],
                        np.int32)
                    assert inst.shape[0] == self.A
                    plan = dict(inst_arm=inst, arm_safe=safe)
        self._por_memo = plan
        self.por_reason = reason
        tel = obs.current()
        if self.por:
            if plan is None:
                self.log(f"-- por requested but reduction disabled: "
                         f"{reason} (running unreduced)")
                tel.gauge("por.disabled_reason", reason)
                tel.gauge("por.enabled", False)
            else:
                n_safe = int(plan["arm_safe"].sum())
                self.log(f"-- por: {n_safe}/{len(self.arms)} arms "
                         f"eligible as singleton ample sets (device "
                         f"persistent-set filter in the fused step)")
                tel.gauge("por.enabled", True)
                tel.gauge("por.engine", "device")
        return plan

    def _por_warnings(self) -> List[str]:
        """The interp backend's refusal warning, word-for-word, when
        --por was requested but the reduction cannot run."""
        if not self.por:
            return []
        if self._por_plan() is None:
            return [f"--por requested but reduction disabled: "
                    f"{self.por_reason} (running unreduced)"]
        return []

    def _por_finish(self, ample: int, expanded: int, masked: int,
                    distinct: int) -> None:
        """Emit the end-of-run POR counters (same names as the interp
        engine, plus the device-only masked-candidate gauge)."""
        if not self.por or self._por_memo in (None, _POR_UNSET):
            return
        tel = obs.current()
        full = max(0, int(expanded) - int(ample))
        tel.counter("por.ample_states", int(ample))
        tel.counter("por.full_states", full)
        tel.gauge("por.ample_ratio",
                  round(int(ample) / int(expanded), 4)
                  if expanded else 0.0)
        tel.gauge("por.device_masked_arms", int(masked))
        tel.gauge("por.reduced_states", int(distinct))

    # ---- lifted constants + follower clones (ISSUE 13) ---------------

    def _install_const_lanes(self, cvec) -> None:
        """Bind the lifted-constant TRACERS into the kernel context for
        the duration of a trace (kernel2 identifier resolution reads
        kc.const_lanes).  No-op for ordinary engines."""
        if self._lift_names:
            self.kc.const_lanes = {
                nm: cvec[i] for i, nm in enumerate(self._lift_names)}

    def _traced_with(self, fn, cvec, *args):
        """Run `fn(*args)` (a trace) with const lanes installed; used
        by the forced abstract traces at build time."""
        self._install_const_lanes(cvec)
        try:
            return fn(*args)
        finally:
            self.kc.const_lanes = {}

    def _cvec_jnp(self):
        if getattr(self, "_cvec_dev", None) is None:
            self._cvec_dev = jnp.asarray(self._cvec)
        return self._cvec_dev

    def batch_block_reason(self) -> Optional[str]:
        """None when this engine can serve as a cross-model batch
        donor/member; otherwise the human-readable blocker (the batch
        planner falls back to solo runs and reports it)."""
        if not self.host_seen:
            return "host_seen mode required"
        if self.hybrid:
            return ("hybrid execution (interp-demoted units): "
                    + "; ".join(
                        [f"arm {a.label or 'Next'}" for a, _ in
                         self.fb_arms]
                        + [f"invariant {nm}" for nm, _, _ in
                           self.fb_invs]
                        + [f"constraint {nm}" for nm, _, _ in
                           self.fb_cons]))
        if self.refiners:
            return "refinement PROPERTYs (stepwise host edge checks)"
        if self.live_obligations:
            return "temporal PROPERTYs (behavior graph)"
        if self._demotable:
            # a fired compile-recovery demotion restarts via
            # _demote_arms, which MUTATES the (donor-shared) compiled
            # arm set mid-cohort — refuse up front; the jobs run solo
            # where the demotion restart is sound
            return ("compile-recovery demotions possible (arms "
                    + ", ".join(self.arms[i].label or "Next"
                                for i in self._demotable)
                    + "): a runtime demotion restart would mutate the "
                      "shared batch program")
        if self.seen_cap is not None:
            return "hierarchical seen-set spill (per-member tiers)"
        fused_max = int(os.environ.get("JAXMC_FUSED_MAX_INSTANCES",
                                       "24"))
        if jax.default_backend() == "cpu" and self.A > fused_max:
            return (f"arm-split step ({self.A} instances > "
                    f"JAXMC_FUSED_MAX_INSTANCES={fused_max})")
        return None

    _DONOR_SHARED = (
        "backend_desc", "bounds", "layout", "kc", "plan", "compiled",
        "actions", "arms", "_ca_arm", "fb_arms", "fb_invs", "fb_cons",
        "inv_fns", "constraint_fns", "canon_fn", "_sym_fallback",
        "sym_identity", "view_fn", "view_width", "refiners",
        "unrefined", "live_obligations", "live_unsupported",
        "collect_edges", "hybrid", "_demotable", "labels_flat",
        "arm_verdicts", "A", "W", "PW", "K", "fp_mode", "key_width",
        "donate", "chunk", "sample_cfg", "host_seen", "seen_mode_req",
        "_lift_names", "_trace_lock",
        # compiled-program caches are SHARED OBJECTS: a follower's
        # first dispatch is a cache hit on the donor's jit, with its
        # own constant vector as a runtime argument
        "_step_cache", "_hstep_cache", "_hstep_group_jits",
        "_newcheck_cache", "_res_cache", "_hostkeys_cache",
        "_pkeys_cache")

    def _clone_from_donor(self, donor: "TpuExplorer", model: Model,
                          log, max_states, store_trace, progress_every,
                          checkpoint_path, checkpoint_every,
                          resume_from, final_checkpoint) -> None:
        """FOLLOWER construction (ISSUE 13): reuse the donor's layout
        and compiled kernels wholesale — zero sampling, zero bounds
        fixpoint, zero kernel builds — binding only this member's
        model, init states and run-control surface.  The caller
        (backend/batch.py) has already proven layout compatibility
        (same module shape; constants outside the lifted set equal) and
        that the donor is batchable (no hybrid units, no refiners, no
        temporal obligations)."""
        reason = donor.batch_block_reason()
        if reason is not None:
            raise ModeError(f"donor engine is not batchable: {reason}")
        for attr in self._DONOR_SHARED:
            setattr(self, attr, getattr(donor, attr))
        self.model = model
        self.log = log if log is not None else obs.Logger(quiet=True)
        self.max_states = max_states
        self.store_trace = store_trace
        self.progress_every = progress_every
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = checkpoint_every
        self.resume_from = resume_from
        self.final_checkpoint = final_checkpoint
        self.resident = False
        self.pin_interp_arms = False
        self.extra_samples = []
        # relayout/demotion restarts rebuild layout+kernels per member,
        # which would diverge from the shared batch program: a follower
        # that hits a recovery abort surfaces it (the batch runner
        # falls back to a solo re-run)
        self.relayouts_left = 0
        self.cap_profile = False
        self._res_caps_hint = None
        self._res_caps = None
        self._res_maxlvl = donor._res_maxlvl
        self._last_frontier_np = None
        self.seen_cap = None
        self.spill_dir = None
        self.host_tier_keys = None
        self._tiers = None
        self._cvec = np.asarray([int(model.defs[n])
                                 for n in self._lift_names], np.int32)
        self._cvec_dev = None
        base_ctx = model.ctx()
        self.init_states = enumerate_init(model.init, base_ctx,
                                          model.vars)

    def _expand_fn(self):
        """The (state x action) expansion closure shared by both step
        builders; slotted kernels vmap over a traced slot index."""
        acts = self.compiled
        if not acts:
            # hybrid with every arm demoted: a zero-instance expansion
            # (jnp.stack refuses empty lists; shapes stay [0, FC(, W)])
            W = self.W

            def expand_none(frontier):
                FC = frontier.shape[0]
                z = jnp.zeros((0, FC), bool)
                return (z, jnp.ones((0, FC), bool),
                        jnp.zeros((0, FC), jnp.int32),
                        jnp.zeros((0, FC, W), jnp.int32))

            return expand_none

        def expand(frontier):
            ens, aoks, ovs, succs = [], [], [], []
            for ca in acts:
                if ca.n_slots:
                    slots = jnp.arange(ca.n_slots, dtype=jnp.int32)
                    en, aok, ov, succ = jax.vmap(
                        jax.vmap(ca.fn, in_axes=(0, None)),
                        in_axes=(None, 0))(frontier, slots)
                    for si in range(ca.n_slots):
                        ens.append(en[si])
                        aoks.append(aok[si])
                        ovs.append(ov[si])
                        succs.append(succ[si])
                else:
                    en, aok, ov, succ = jax.vmap(ca.fn)(frontier)
                    ens.append(en)
                    aoks.append(aok)
                    ovs.append(ov)
                    succs.append(succ)
            return (jnp.stack(ens), jnp.stack(aoks), jnp.stack(ovs),
                    jnp.stack(succs))

        return expand

    def _candidate_block_fn(self, FC: int):
        """Shared mesh-step prologue (ISSUE 8): expand one frontier
        block of capacity FC and produce the flat candidate block with
        its dedup keys, packed rows and fault scalars.  Both mesh step
        builders (the legacy exchange step and the device-resident
        level step, tpu/mesh.py) start from exactly this closure so the
        candidate semantics — validity masking, pack-guard overflow
        folding (OV_PACK under kernel codes), assert/deadlock
        provenance — cannot drift between them.

        Returns a closure (frontier_lanes, fvalid) -> dict with keys:
          gen_local, overflow (max OV_* code, 0 = none),
          ckeys [C,K], cand [C,PW] packed, cand_u [C,W], cvalid [C],
          dead [FC] bool, dead_slot, assert_bad (scalar), asrt_a, asrt_f
        where C = A * FC."""
        A, W = self.A, self.W
        C = A * FC
        keys_of = self._keys_of
        expand = self._expand_fn()

        def block(frontier, fvalid):
            en, aok, ov, succ = expand(frontier)
            valid = en & fvalid[None, :]
            abad = (~aok) & fvalid[None, :]
            assert_bad = jnp.any(abad)
            aflat = jnp.argmax(abad.reshape(-1))
            asrt_a = (aflat // FC).astype(jnp.int32)
            asrt_f = (aflat % FC).astype(jnp.int32)
            overflow = jnp.max(jnp.where(fvalid[None, :], ov, 0)) \
                .astype(jnp.int32)
            dead = fvalid & ~jnp.any(en, axis=0)
            dead_slot = jnp.argmax(dead).astype(jnp.int32)
            gen_local = jnp.sum(valid)
            cand_u = succ.reshape(C, W)
            cvalid = valid.reshape(C)
            cand_u = jnp.where(cvalid[:, None], cand_u, SENTINEL)
            ckeys, cand, pack_ovf = keys_of(cand_u, cvalid)
            overflow = jnp.where(
                overflow != 0, overflow,
                jnp.where(pack_ovf, OV_PACK, 0).astype(jnp.int32))
            return dict(gen_local=gen_local, overflow=overflow,
                        ckeys=ckeys, cand=cand, cand_u=cand_u,
                        cvalid=cvalid, dead=dead, dead_slot=dead_slot,
                        assert_bad=assert_bad, asrt_a=asrt_a,
                        asrt_f=asrt_f)

        return block

    def _temporal_warnings(self) -> List[str]:
        out = []
        if self.live_unsupported:
            out.append(
                "temporal properties NOT checked (unsupported form): "
                + ", ".join(self.live_unsupported))
        for rc in self.refiners:
            if rc.liveness_skipped:
                out.append(
                    f"property {rc.name}: refinement checked stepwise; "
                    f"its fairness conjuncts are NOT checked")
        return out

    def _check_live(self, graph, warnings) -> Optional[Violation]:
        """Run the temporal obligations over the accumulated behavior
        graph (end of a completed search)."""
        if not self.live_obligations:
            return None
        from ..engine.liveness import LivenessChecker
        states = [self.layout.decode_packed(r) for r in graph.rows]
        lc = LivenessChecker(self.model, states, graph.edges,
                             graph.parents, graph.labels)
        bad, live_warns = lc.check(self.live_obligations)
        warnings.extend(live_warns)
        if bad is None:
            return None
        pname, trace, msg = bad
        return Violation("property", pname, trace, msg)

    def _refine_init(self, init_rows, explored_init):
        """check_init on kept init states; (rc_name, state) | None."""
        if not self.refiners:
            return None
        for i in explored_init:
            st = self.layout.decode(init_rows[i])
            for rc in self.refiners:
                if not rc.check_init(st):
                    return rc.name, st
        return None

    def _refine_edges(self, frontier_rows, cand, cvalid, explore, FC):
        """Stepwise refinement over this level's kept candidate edges
        (decode on host, same check the interp engine runs). Returns
        (action_idx, frontier_idx, succ_state, checker) or None.
        Duplicate (parent, succ) pairs are checked once per run."""
        if not self.refiners:
            return None
        idxs = np.nonzero(np.asarray(cvalid) & np.asarray(explore))[0]
        if not len(idxs):
            return None
        cand = np.asarray(cand)
        frontier_rows = np.asarray(frontier_rows)
        parents: Dict[int, Any] = {}
        if len(self._ref_pair_cache) > (1 << 20):
            self._ref_pair_cache.clear()
        for c in idxs:
            f = int(c % FC)
            a = int(c // FC)
            key = (frontier_rows[f].tobytes(), cand[c].tobytes())
            if key in self._ref_pair_cache:
                continue
            self._ref_pair_cache.add(key)
            pst = parents.get(f)
            if pst is None:
                pst = self.layout.decode_packed(frontier_rows[f])
                parents[f] = pst
            sst = self.layout.decode_packed(cand[c])
            for rc in self.refiners:
                if not rc.check_edge(pst, sst):
                    return a, f, sst, rc
        return None

    def _refine_msg(self, rc) -> str:
        msg = (f"step is not a [{rc.name}-Next]_v step of the refined "
               f"specification")
        if rc.last_error:
            msg += f"; while evaluating the property: {rc.last_error}"
        return msg

    def _refine_violation(self, rc, sst, a, trace):
        trace = [x for x in trace if x[0] is not None]
        trace.append((sst, self.labels_flat[a]))
        return Violation("property", rc.name, trace, self._refine_msg(rc))

    def _symmetry_warnings(self) -> List[str]:
        if self.model.symmetry is None or self.canon_fn is not None \
                or self.sym_identity:
            # identity groups have no reduction to fall back FROM:
            # counts match the (equally unreduced) TLC/interp search,
            # so warning of divergence would be wrong in kind
            return []
        return [SYMMETRY_WARNING + (f" ({self._sym_fallback})"
                                    if self._sym_fallback else "")]

    def _keys_of(self, rows, valid):
        """(keys, packed_rows, pack_ovf) for a block of UNPACKED rows.

        keys: [N, K] dedup key lanes — an explicit validity lane FIRST
        (0=valid, 1=invalid, sorting after all valid rows; SENTINEL
        data), then the key basis: the cfg VIEW's value lanes when one
        is declared, else the BIT-PACKED row (compile/pack.py) —
        fingerprinted to 4 words in fp mode.

        packed_rows: [N, PW] the packed rows for engine storage
        (SENTINEL-filled where invalid).

        pack_ovf: scalar bool — some VALID row had a guarded lane
        outside its profiled bit range; the engines route it into the
        overflow channel as kernel2.OV_PACK (an exact abort naming
        JAXMC_PACK=0, never a silently wrong count).

        With cfg SYMMETRY, the KEY basis is the orbit's canonical
        representative (compile/symmetry2.py) while the stored packed
        row keeps the original state — same partition, same traces, as
        the unpacked engines."""
        packed, povf = self.plan.pack_rows(rows)
        pack_ovf = jnp.any(povf & valid)
        packed = jnp.where(valid[:, None], packed, SENTINEL)
        if self.view_fn is not None:
            # SYMMETRY composes with VIEW exactly like the interp's
            # state_fingerprint: the view evaluates over the orbit's
            # CANONICAL representative (view of the raw row would count
            # symmetric states as distinct — caught in review by a
            # 2-process SYMMETRY+VIEW repro, 17/9 vs the interp's 12/6)
            vrows = rows
            if self.canon_fn is not None:
                vrows = jnp.where(valid[:, None], self.canon_fn(rows),
                                  rows)
            kb = jax.vmap(self.view_fn)(vrows)
            if kb.ndim == 1:
                kb = kb[:, None]
        elif self.canon_fn is not None:
            crows = jnp.where(valid[:, None], self.canon_fn(rows), rows)
            kb, cpovf = self.plan.pack_rows(crows)
            kb = jnp.where(valid[:, None], kb, SENTINEL)
            pack_ovf = pack_ovf | jnp.any(cpovf & valid)
        else:
            kb = packed
        k = fingerprint128(kb) if self.fp_mode else kb
        k = jnp.where(valid[:, None], k, SENTINEL)
        vlane = jnp.where(valid, 0, 1).astype(jnp.int32)
        return (jnp.concatenate([vlane[:, None], k], axis=1), packed,
                pack_ovf)

    def _host_keys(self, rows_np):
        """Host-side (keys, packed, pack_ovf) over unpacked numpy rows —
        the init/fallback boundary paths.  numpy in, numpy out.  Jitted
        per power-of-two bucket: the eager op-by-op dispatch of the
        pack + fingerprint chain costs ~20ms even for a handful of rows
        (measured on viewtoy), which dominated warm whole-run walls."""
        n = len(rows_np)
        if n == 0:
            return (np.zeros((0, self.K), np.int32),
                    np.zeros((0, self.PW), np.int32), False)
        cap = _pow2_at_least(n, lo=8)
        jf = self._hostkeys_cache.get(cap)
        if jf is None:
            jf = obs.prof_wrap("bfs.host_keys", jax.jit(
                lambda rows, valid: self._keys_of(rows, valid)))
            self._hostkeys_cache[cap] = jf
        buf = np.repeat(np.asarray(rows_np[:1], np.int32), cap, axis=0)
        buf[:n] = rows_np
        k, p, o = jf(jnp.asarray(buf),
                     jnp.asarray(np.arange(cap) < n))
        return np.asarray(k)[:n], np.asarray(p)[:n], bool(o)

    # ---- hierarchical seen set (ISSUE 12): spill + cold-tier probes --

    def _ensure_tiers(self):
        """The cold-tier store, created at the first spill (zero cost —
        and zero behavior change — for runs that never overflow)."""
        if self._tiers is None:
            from .tiers import TieredSeen
            self._tiers = TieredSeen(
                self.K - 1, host_budget_keys=self.host_tier_keys,
                spill_dir=self.spill_dir, log=self.log)
        return self._tiers

    def _tier_spill_prefix(self, seen_np: np.ndarray, count: int) -> None:
        """Compact the device table's sorted valid prefix out as ONE
        immutable sorted run (the validity lane is stripped — cold runs
        hold data words only)."""
        if count <= 0:
            return
        t = self._ensure_tiers()
        t.spill(np.ascontiguousarray(seen_np[:count, 1:]))
        obs.current().counter("tier.spilled_keys", int(count))

    def _packed_keys(self, packed_np: np.ndarray) -> np.ndarray:
        """Dedup-key DATA words ([n, K-1], validity lane stripped) for a
        block of PACKED rows — the cold-tier probe basis for frontier
        rows pulled back from the device.  Jitted per power-of-two
        bucket like _host_keys."""
        n = len(packed_np)
        if n == 0:
            return np.zeros((0, self.K - 1), np.int32)
        cap = _pow2_at_least(n, lo=64)
        jf = self._pkeys_cache.get(cap)
        if jf is None:
            plan = self.plan
            keys_of = self._keys_of

            @jax.jit
            def pk(packed, valid):
                rows = plan.unpack_rows(packed)
                return keys_of(rows, valid)[0]

            self._pkeys_cache[cap] = jf = obs.prof_wrap(
                "bfs.packed_keys", pk)
        buf = np.repeat(np.asarray(packed_np[:1], np.int32), cap, axis=0)
        buf[:n] = packed_np
        k = jf(jnp.asarray(buf), jnp.asarray(np.arange(cap) < n))
        return np.asarray(k)[:n, 1:]

    def _tier_keep_mask(self, rows_np: np.ndarray) -> np.ndarray:
        """[n] bool keep-mask over packed rows: False where the row's
        dedup key already lives in a cold tier (it was admitted before
        the spill, so the uncapped run would never have re-frontiered
        it)."""
        if self._tiers is None or not self._tiers.active \
                or len(rows_np) == 0:
            return np.ones(len(rows_np), bool)
        return ~self._tiers.probe(self._packed_keys(rows_np))

    # ---- jitted level step, compiled per (seen_cap, frontier_cap) ----
    def _get_step(self, SC: int, FC: int) -> Callable:
        # rank-merge port (ISSUE 11 tentpole b): the level mode is the
        # LEGACY host loop refinement/temporal-PROPERTY checking runs on
        # (the resident loop cannot stream edges), and it full-sorted
        # seen+candidates — a [SC+C, K+1]-key stable sort EVERY level —
        # long after the resident engines went O(new).  The seen table
        # already keeps a sorted valid prefix (init lexsorts, the merge
        # writes sorted output), so bfs._rank_merge drops the per-level
        # sort work to the C candidate keys alone.  Counts, traces and
        # frontier order are bit-identical (pinned by tests);
        # JAXMC_LEVEL_RANKMERGE=0 keeps the full-sort as the escape
        # hatch / parity oracle.
        rank = os.environ.get("JAXMC_LEVEL_RANKMERGE", "").strip() != "0"
        # tiered runs (ISSUE 12) also stream each kept row's dedup key
        # to the host, so the cold-tier membership probe never
        # recomputes keys; the flag joins the compile key — the one
        # recompile it costs happens at the first spill
        tiered = self._tiers is not None
        # device POR (ISSUE 18): the persistent-set filter joins the
        # compile key — the mask arrays are baked constants
        por_plan = self._por_plan() if self.por else None
        por = por_plan is not None
        key = (SC, FC, rank, tiered, por)
        if key in self._step_cache:
            obs.current().counter("compile.cache_hits")
            return self._step_cache[key]
        obs.current().counter("compile.cache_misses")
        A, W, K, PW = self.A, self.W, self.K, self.PW
        plan = self.plan
        inv_fns = self.inv_fns
        con_fns = self.constraint_fns
        keys_of = self._keys_of
        expand = self._expand_fn()
        # stream candidates for stepwise refinement and/or the liveness
        # behavior graph on the host (verdict parity with the interp)
        need_edges = bool(self.refiners) or self.collect_edges
        if por:
            # temporal/refinement PROPERTYs are por_refusal territory,
            # so the edge stream and the mask can never co-occur
            assert not need_edges
            por_inst = jnp.asarray(por_plan["inst_arm"])
            por_safe_v = jnp.asarray(por_plan["arm_safe"])
        # FUSED + DONATED level step (ISSUE 6): the whole level —
        # expansion, fingerprint/pack, dedup sort, CONSTRAINT and
        # invariant evaluation — is ONE jitted dispatch, and the seen
        # table (always) plus the frontier (unless the run streams
        # edges, which reads the frontier after the step) are donated so
        # XLA updates them in place instead of copying per level.
        donate = (0, 2) if self.donate and not need_edges \
            else ((0,) if self.donate else ())

        @partial(jax.jit, donate_argnums=donate)
        def step(seen_keys, seen_count, frontier_p, fcount):
            frontier = plan.unpack_rows(frontier_p)
            fvalid = jnp.arange(FC) < fcount
            en, aok, ov, succ = expand(frontier)
            valid = en & fvalid[None, :]
            assert_bad = (~aok) & fvalid[None, :]
            # ov carries the int overflow CODE (kernel2.OV_*): keep the
            # max so the engine can tell demotion aborts from capacity
            overflow = jnp.where(fvalid[None, :], ov, 0)
            dead = fvalid & ~jnp.any(en, axis=0)
            gen = jnp.sum(valid)

            C = A * FC
            cand_u = succ.reshape(C, W)
            cvalid = valid.reshape(C)
            prov = jnp.arange(C, dtype=jnp.int32)
            cand_u = jnp.where(cvalid[:, None], cand_u, SENTINEL)
            ckeys, cand, pack_ovf = keys_of(cand_u, cvalid)

            por_ample = por_expanded = por_masked = jnp.int32(0)
            if por:
                # persistent-set filter INSIDE the fused step (ISSUE
                # 18): probe the PRE-level seen snapshot (closure
                # through this depth — see _por_mask for the cycle-
                # proviso argument), then mask every non-ample arm's
                # candidates.  Deadlock/assert verdicts above read the
                # PRE-mask enabledness; gen counts the reduced stream.
                found, _ = _seen_probe(seen_keys, seen_count, ckeys, SC)
                keep, por_ample, por_expanded = _por_mask(
                    found, cvalid, por_inst, por_safe_v, A, FC)
                por_masked = jnp.sum(cvalid & ~keep, dtype=jnp.int32)
                inv_key = jnp.concatenate([
                    jnp.ones((C, 1), jnp.int32),
                    jnp.full((C, K - 1), SENTINEL, jnp.int32)], axis=1)
                ckeys = jnp.where(keep[:, None], ckeys, inv_key)
                cand_u = jnp.where(keep[:, None], cand_u, SENTINEL)
                cvalid = keep
                gen = jnp.sum(keep)

            if rank:
                # O(new): sort only the C candidate keys, dedup against
                # the sorted seen prefix with binary searches, scatter
                # the new keys at their ranks.  nk_sidx is each new
                # key's original candidate index in key-sorted order —
                # exactly the full sort's new_cidx (stable ties keep
                # first occurrence in both).  The caller pre-grows SC
                # so seen_count + C <= SC: seen_count2 never overflows.
                rm = _rank_merge(seen_keys, seen_count, ckeys, C, SC, K,
                                 multikey=True)
                new_count = rm["new_count"]
                safe_cidx = jnp.clip(rm["nk_sidx"], 0, C - 1)
                seen2 = rm["seen2"]
                seen_count2 = rm["seen_count2"]
            else:
                # argsort on keys only, then gather payloads by
                # permutation — a variadic sort carrying all W lanes
                # compiles and runs far slower than sort(keys, index) +
                # take
                allk = jnp.concatenate([seen_keys, ckeys])   # [SC+C, K]
                flag = jnp.concatenate([
                    jnp.zeros(SC, jnp.int32), jnp.ones(C, jnp.int32)])
                idx0 = jnp.arange(SC + C, dtype=jnp.int32)
                ops = tuple(allk[:, i] for i in range(K)) + (flag, idx0)
                sorted_ = lax.sort(ops, num_keys=K + 1, is_stable=True)
                skeys = jnp.stack(sorted_[:K], axis=1)
                sflag = sorted_[K]
                perm = sorted_[K + 1]
                # candidate payload indices: position in cand (<0: seen)
                cidx = perm - SC  # >=0 only for candidate entries
                rvalid = skeys[:, 0] == 0
                neq_prev = jnp.concatenate([
                    jnp.array([True]),
                    jnp.any(skeys[1:] != skeys[:-1], axis=1)])
                new = (sflag == 1) & rvalid & neq_prev
                new_count = jnp.sum(new)

                # compact new entries to the front (stable, keeps key
                # order)
                ops2 = ((1 - new.astype(jnp.int32)), cidx)
                comp = lax.sort(ops2, num_keys=1, is_stable=True)
                new_cidx = comp[1][:C]
                safe_cidx = jnp.clip(new_cidx, 0, C - 1)

                # merged seen keys, compacted and sorted
                keep = ((sflag == 0) & rvalid) | new
                ops3 = ((1 - keep.astype(jnp.int32)),) + \
                    tuple(skeys[:, i] for i in range(K))
                comp3 = lax.sort(ops3, num_keys=1, is_stable=True)
                seen2 = jnp.stack(comp3[1:], axis=1)[:SC]
                seen_count2 = jnp.sum(keep)

            new_rows = jnp.take(cand, safe_cidx, axis=0)      # packed
            new_rows_u = jnp.take(cand_u, safe_cidx, axis=0)  # lanes
            new_prov = jnp.take(prov, safe_cidx)
            nvalid = jnp.arange(C) < new_count
            new_rows = jnp.where(nvalid[:, None], new_rows, SENTINEL)

            # constraints FIRST: violating states are fingerprinted (they
            # are in seen2 above) but discarded — never counted distinct,
            # never invariant-checked, never explored. TLC semantics,
            # pinned by the golden run (testout2:265, 195 distinct)
            explore = nvalid
            for nm, f in con_fns:
                explore = explore & jax.vmap(f)(new_rows_u)
            explore_count = jnp.sum(explore)
            # the next frontier is ordered by PROVENANCE (frontier-slot
            # major, action minor — the interpreter's discovery order),
            # not by dedup-key order: key order depends on the packed
            # encoding, so ordering by it would let the bit layout pick
            # WHICH equally-short counterexample gets reported (packed
            # and unpacked runs must produce identical traces)
            fmaj = (new_prov % FC) * jnp.int32(max(A, 1)) + \
                new_prov // FC
            idx4 = jnp.arange(C, dtype=jnp.int32)
            ops4 = ((1 - explore.astype(jnp.int32)), fmaj, idx4)
            comp4 = lax.sort(ops4, num_keys=2, is_stable=True)
            perm4 = comp4[2]
            front_rows = jnp.take(new_rows, perm4, axis=0)
            front_rows_u = jnp.take(new_rows_u, perm4, axis=0)
            front_prov = jnp.take(new_prov, perm4)
            frontvalid = jnp.arange(C) < explore_count
            front_keys = None
            if tiered:
                new_keys = jnp.take(ckeys, safe_cidx, axis=0)
                front_keys = jnp.take(new_keys, perm4, axis=0)

            # invariants over the kept (explored) states only
            inv_bad_any = jnp.asarray(False)
            inv_bad_idx = jnp.asarray(0, jnp.int32)
            inv_bad_which = jnp.asarray(-1, jnp.int32)
            for wi, (nm, f) in enumerate(inv_fns):
                ok = jax.vmap(f)(front_rows_u)
                bad = frontvalid & ~ok
                any_ = jnp.any(bad)
                idx = jnp.argmax(bad)
                first = jnp.logical_and(any_, ~inv_bad_any)
                inv_bad_idx = jnp.where(first, idx, inv_bad_idx)
                inv_bad_which = jnp.where(first, wi, inv_bad_which)
                inv_bad_any = inv_bad_any | any_

            # kernel overflow codes outrank the pack guard: OV_DEMOTED
            # must reach the engine so the hybrid restart can fire
            base_ov = jnp.max(overflow, initial=0)
            ov_out = jnp.where(base_ov != 0, base_ov,
                               jnp.where(pack_ovf, OV_PACK, 0))
            out = dict(gen=gen, dead=dead, assert_bad=assert_bad,
                       overflow=ov_out,
                       seen=seen2, seen_count=seen_count2,
                       front_rows=front_rows, front_prov=front_prov,
                       front_count=explore_count,
                       inv_bad_any=inv_bad_any, inv_bad_idx=inv_bad_idx,
                       inv_bad_which=inv_bad_which)
            if por:
                out["por_ample"] = por_ample
                out["por_expanded"] = por_expanded
                out["por_masked"] = por_masked
            if front_keys is not None:
                out["front_keys"] = front_keys
            if need_edges:
                exp_all = cvalid
                for nm, f in con_fns:
                    exp_all = exp_all & jax.vmap(f)(cand_u)
                out["cand"] = cand
                out["cvalid"] = cvalid
                out["explore_all"] = exp_all
            return out

        step = obs.prof_wrap("bfs.level_step", step)
        self._step_cache[key] = step
        return step

    def _hstep_core(self, FC: int) -> Callable:
        """The UNJITTED fused host_seen step:
        (frontier_p [FC, PW], fcount, cvec [n_lift] i32) -> out dict.
        One unit, two compilers: the solo engine jits it directly
        (_get_hstep), the cross-model batcher (backend/batch.py) jits
        jax.vmap of it so B members' frontiers + per-model constant
        vectors go through ONE dispatch.  `cvec` is the lifted-constant
        vector (empty for ordinary engines); the tracer install at the
        top is what makes the compiled program constant-generic."""
        A, W = self.A, self.W
        plan = self.plan
        inv_fns = self.inv_fns
        con_fns = self.constraint_fns
        keys_of = self._keys_of
        install = self._install_const_lanes

        def hstep_core(frontier_p, fcount, cvec):
            install(cvec)
            frontier = plan.unpack_rows(frontier_p)
            fvalid = jnp.arange(FC) < fcount
            en, aok, ov, succ = self._expand_fn()(frontier)
            valid = en & fvalid[None, :]
            assert_bad = (~aok) & fvalid[None, :]
            # int overflow CODE (kernel2.OV_*), max-reduced below
            overflow = jnp.where(fvalid[None, :], ov, 0)
            dead = fvalid & ~jnp.any(en, axis=0)
            gen = jnp.sum(valid)
            C = A * FC
            cand_u = succ.reshape(C, W)
            cvalid = valid.reshape(C)
            cand_u = jnp.where(cvalid[:, None], cand_u, SENTINEL)
            keys, cand, pack_ovf = keys_of(cand_u, cvalid)
            inv_ok = jnp.ones(C, bool)
            for nm, f in inv_fns:
                inv_ok = inv_ok & jax.vmap(f)(cand_u)
            explore = jnp.ones(C, bool)
            for nm, f in con_fns:
                explore = explore & jax.vmap(f)(cand_u)
            base_ov = jnp.max(overflow, initial=0)
            ov_out = jnp.where(base_ov != 0, base_ov,
                               jnp.where(pack_ovf, OV_PACK, 0))
            # trace hygiene: clear the shared ctx so no stale tracers
            # outlive this trace (every read happened above)
            self.kc.const_lanes = {}
            return dict(cand=cand, cvalid=cvalid, keys=keys, gen=gen,
                        dead=dead, assert_bad=assert_bad,
                        overflow=ov_out,
                        inv_ok=inv_ok, explore=explore)

        return hstep_core

    def _get_hstep(self, FC: int) -> Callable:
        """Expand-only step for host_seen mode: the seen-set lives in the
        native C++ fingerprint store (native/fps_store.cc) — the spill
        layer of SURVEY.md §7.5 — so the device does expansion, hashing,
        and predicate checks while membership runs on the host."""
        if FC in self._hstep_cache:
            obs.current().counter("compile.cache_hits")
            return self._hstep_cache[FC]
        obs.current().counter("compile.cache_misses")
        A, W, PW = self.A, self.W, self.PW
        plan = self.plan
        con_fns = self.constraint_fns
        keys_of = self._keys_of

        # SPLIT vs FUSED compilation (VERDICT r3 weak #3, retuned by
        # ISSUE 6): one fused jit over all A kernels compiles
        # superlinearly on XLA:CPU (MCVoting's 60 instances: >10 min
        # fused vs ~2 min as 60 small programs + one tiny combine) — but
        # always-split-on-CPU made every SMALL model pay A dispatches +
        # a combine + deferred predicate dispatches per chunk, one of
        # the constant factors behind the r04 kernel-slower-than-interp
        # inversion.  The fused step (expansion + predicates + pack +
        # fingerprint in ONE dispatch per chunk) is now the default
        # whenever the instance count is modest; only many-instance
        # models split on CPU (JAXMC_FUSED_MAX_INSTANCES, default 24).
        fused_max = int(os.environ.get("JAXMC_FUSED_MAX_INSTANCES",
                                       "24"))
        split = jax.default_backend() == "cpu" and A > fused_max \
            and not self._lift_names

        if not split:
            core_j = obs.prof_wrap("bfs.hstep",
                                   jax.jit(self._hstep_core(FC)))
            cvec = self._cvec_jnp()

            def hstep(frontier_p, fcount):
                return core_j(frontier_p, fcount, cvec)

            hstep.is_async = True  # fused jit: dispatch is asynchronous
            self._hstep_cache[FC] = hstep
            return hstep

        # ARM-GROUP fused jits (ISSUE 7 satellite, lifting the ROADMAP
        # item-2 remainder): the old fallback compiled one jit PER
        # ACTION (A dispatches + A host round-trips per chunk — pure
        # overhead, the r04 inversion's constant factor writ large on
        # many-instance models).  Instead, partition the compiled
        # actions into groups of <= fused_max INSTANCES and fuse each
        # group into ONE jit: XLA:CPU's superlinear fused-compile cost
        # stays bounded by the group size while the dispatch count
        # drops from A to ceil(A/fused_max).  Candidate order is
        # preserved (groups are contiguous in self.compiled order and
        # concatenate in order), so counts and traces stay identical
        # to both the per-action and the fully-fused paths.
        #
        # Predicates are NOT evaluated per candidate here: the engine
        # only consults inv_ok/explore on NEW rows (a handful per level)
        # — MCVoting's quantifier-heavy Inv over every one of the
        # A*CH = 123k padded candidates per chunk was the r3 sweep's
        # >900 s timeout. The per-candidate explore mask is computed
        # only when the edge stream needs it (refinement/liveness).
        acts = self.compiled
        need_edges = bool(self.refiners) or self.collect_edges

        @jax.jit
        def combine(cand_u, cvalid):
            cand_u = jnp.where(cvalid[:, None], cand_u, SENTINEL)
            keys, cand, pack_ovf = keys_of(cand_u, cvalid)
            if not need_edges:
                return cand, keys, pack_ovf, None
            explore = jnp.ones(cand_u.shape[0], bool)
            for nm, f in con_fns:
                explore = explore & jax.vmap(f)(cand_u)
            return cand, keys, pack_ovf, explore

        combine = obs.prof_wrap("bfs.hstep_combine", combine)
        unpack_j = obs.prof_wrap("bfs.unpack",
                                 jax.jit(plan.unpack_rows))

        def hstep(frontier_p, fcount):
            fvalid = np.arange(FC) < int(fcount)
            if not acts:
                # hybrid with every arm demoted: the device only hashes
                z = np.zeros(0, bool)
                out = dict(cand=jnp.zeros((0, PW), jnp.int32),
                           cvalid=jnp.asarray(z),
                           keys=jnp.zeros((0, self.K), jnp.int32),
                           gen=0, dead=jnp.asarray(fvalid),
                           assert_bad=jnp.zeros((0, FC), bool),
                           overflow=0, deferred_preds=True)
                if need_edges:
                    out["explore"] = jnp.asarray(z)
                return out
            frontier = unpack_j(frontier_p)
            # grouped dispatches SCATTER into original instance order
            # (independence regrouping may have permuted the arms; the
            # candidate stream must stay byte-identical)
            jits, inst_blocks = self._hstep_groups(fused_max)
            en = np.empty((A, FC), bool)
            aok = np.empty((A, FC), bool)
            ov = np.empty((A, FC), np.int32)
            succ_all = np.empty((A, FC, W), np.int32)
            for jf, ii in zip(jits, inst_blocks):
                en_g, aok_g, ov_g, succ_g = jf(frontier)  # [a_g, FC(,W)]
                en[ii] = np.asarray(en_g)
                aok[ii] = np.asarray(aok_g)
                ov[ii] = np.asarray(ov_g)
                succ_all[ii] = np.asarray(succ_g)
            valid = en & fvalid[None, :]
            assert_bad = (~aok) & fvalid[None, :]
            overflow = int(np.where(fvalid[None, :], ov, 0).max(
                initial=0))
            dead = fvalid & ~en.any(axis=0)
            gen = int(valid.sum())
            cand_u = succ_all.reshape(A * FC, W)
            cvalid = valid.reshape(A * FC)
            cand, keys, pack_ovf, explore = combine(
                jnp.asarray(cand_u), jnp.asarray(cvalid))
            if overflow == 0 and bool(pack_ovf):
                overflow = OV_PACK
            out = dict(cand=cand, cvalid=jnp.asarray(cvalid), keys=keys,
                       gen=gen, dead=jnp.asarray(dead),
                       assert_bad=jnp.asarray(assert_bad),
                       overflow=overflow, deferred_preds=True)
            if explore is not None:
                out["explore"] = explore
            return out

        self._hstep_cache[FC] = hstep
        return hstep

    def _arm_group_plan(self, fused_max: int) -> List[List[int]]:
        """Compiled-action index groups for the fused arm-group paths
        (bfs host_seen split + mesh grouped expand).  Default plan is
        the legacy contiguous first-fit; with the independence matrix
        (ISSUE 15, JAXMC_ANALYZE_INDEP=0 opts out) commuting arms
        cluster into the same dispatch and the plan with FEWER groups
        wins.  Callers restore provenance order at the merge, so any
        plan here is count/trace byte-identical."""
        from ..analyze.independence import (indep_enabled,
                                            independence_report,
                                            plan_arm_groups)
        weights = [max(1, ca.n_slots) for ca in self.compiled]
        commutes = None
        if indep_enabled() and self.arms:
            try:
                irep = independence_report(self.model, self.arms)
                commutes = irep.commutes
                obs.current().gauge("analyze.independence_pairs",
                                    irep.commuting_pairs())
                obs.current().gauge("analyze.independence_safe",
                                    len(irep.por_safe))
            except Exception:
                if os.environ.get("JAXMC_DEBUG"):
                    raise
                commutes = None
        groups = plan_arm_groups(weights, list(self._ca_arm), commutes,
                                 fused_max)
        flat = [i for g in groups for i in g]
        obs.current().gauge("expand.regrouped",
                            int(flat != list(range(len(weights)))))
        return groups

    def _group_inst_blocks(self, groups: List[List[int]]
                           ) -> List[np.ndarray]:
        """Per-group FLAT INSTANCE indices (into the [A, ...] expansion
        axis) — the scatter targets that restore original provenance
        order after grouped dispatches."""
        w = [max(1, ca.n_slots) for ca in self.compiled]
        off = np.concatenate([[0], np.cumsum(w)]).astype(np.int64)
        return [np.concatenate([np.arange(off[i], off[i] + w[i])
                                for i in g]).astype(np.int64)
                for g in groups]

    def _hstep_groups(self, fused_max: int):
        """The arm-group fused expansion jits for the many-instance
        host_seen path: groups of compiled actions, each holding at
        most `fused_max` kernel INSTANCES (a single action whose slot
        fan-out alone exceeds the cap gets its own group — the cap
        bounds the fused-compile blowup, and one slotted kernel is a
        single program regardless of its slot count).  One jit per
        group.  Returns (jits, inst_blocks): inst_blocks[g] holds the
        original flat instance indices of group g's output rows, and
        the caller SCATTERS them back, so the candidate stream is
        identical to the per-action and fully-fused paths even when
        independence-driven regrouping reordered the arms."""
        cached = self._hstep_group_jits.get(fused_max)
        if cached is not None:
            obs.current().counter("compile.cache_hits")
            return cached
        obs.current().counter("compile.cache_misses")
        plan = self._arm_group_plan(fused_max)
        groups = [[self.compiled[i] for i in g] for g in plan]
        inst_blocks = self._group_inst_blocks(plan)

        def _mk(subset):
            def gexpand(frontier):
                ens, aoks, ovs, succs = [], [], [], []
                for ca in subset:
                    if ca.n_slots:
                        slots = jnp.arange(ca.n_slots, dtype=jnp.int32)
                        en, aok, ov, succ = jax.vmap(
                            jax.vmap(ca.fn, in_axes=(0, None)),
                            in_axes=(None, 0))(frontier, slots)
                        for si in range(ca.n_slots):
                            ens.append(en[si])
                            aoks.append(aok[si])
                            ovs.append(ov[si])
                            succs.append(succ[si])
                    else:
                        en, aok, ov, succ = jax.vmap(ca.fn)(frontier)
                        ens.append(en)
                        aoks.append(aok)
                        ovs.append(ov)
                        succs.append(succ)
                return (jnp.stack(ens), jnp.stack(aoks),
                        jnp.stack(ovs), jnp.stack(succs))

            return obs.prof_wrap("bfs.hstep_group", jax.jit(gexpand))

        jits = [_mk(g) for g in groups]
        obs.current().gauge("expand.fused_groups", len(jits))
        out = (jits, inst_blocks)
        self._hstep_group_jits[fused_max] = out
        return out

    def _check_new_rows(self, rows_np, skip_cons=False):
        """Compiled invariant (+ constraint unless skip_cons — the edge
        stream already computed per-candidate explore) checks over a
        batch of NEW (packed) rows (split host_seen mode defers them
        from the candidate stream). Pads to a power-of-two bucket (jit
        per bucket, cached) by repeating the first row so the padding is
        always a benign valid encoding."""
        n = len(rows_np)
        if n == 0:
            return np.zeros(0, bool), np.zeros(0, bool)
        cap = _pow2_at_least(n, lo=64)
        ckey = (cap, skip_cons)
        jf = self._newcheck_cache.get(ckey)
        if jf is not None:
            obs.current().counter("compile.cache_hits")
        else:
            obs.current().counter("compile.cache_misses")
            inv_fns = self.inv_fns
            con_fns = [] if skip_cons else self.constraint_fns
            plan = self.plan
            install = self._install_const_lanes

            @jax.jit
            def chk(rows_p, cvec):
                install(cvec)
                rows = plan.unpack_rows(rows_p)
                ok = jnp.ones(rows.shape[0], bool)
                for nm, f in inv_fns:
                    ok = ok & jax.vmap(f)(rows)
                ex_ = jnp.ones(rows.shape[0], bool)
                for nm, f in con_fns:
                    ex_ = ex_ & jax.vmap(f)(rows)
                self.kc.const_lanes = {}  # trace hygiene (see core)
                return ok, ex_

            self._newcheck_cache[ckey] = jf = obs.prof_wrap(
                "bfs.newcheck", chk)
        buf = np.repeat(rows_np[:1], cap, axis=0)
        buf[:n] = rows_np
        # the shared trace lock serializes first-call tracing of the
        # (donor-shared) jit against concurrent member threads: two
        # traces installing const lanes into the ONE shared KernelCtx
        # would cross-contaminate (unreachable in the fused batch path,
        # which never defers predicate checks — belt and braces)
        with self._trace_lock:
            ok, ex_ = jf(jnp.asarray(buf), self._cvec_jnp())
        return np.asarray(ok)[:n], np.asarray(ex_)[:n]

    # ---- resident mode: the whole BFS inside one jitted while_loop ----
    #
    # Motivation (measured): the axon tunnel to the TPU has ~160ms
    # round-trip latency and ~20MB/s effective host<->device bandwidth, so
    # any per-chunk (or even per-level) host participation dominates wall
    # time. Here the seen-set (fingerprint keys), the frontier, and the
    # level loop itself are all device-resident inside lax.while_loop; the
    # host sees one small summary vector per MAXLVL-level batch. Capacity
    # overflows roll back to the last completed level (the carry keeps the
    # pre-level state) and report a grow-and-redo status, so counts stay
    # exact across regrowth.

    def _get_resident_run(self, SC, FCap, AccCap, VC, CH):
        # maxlvl (levels per dispatch) is a TRACED argument, not part of
        # the compile key: the host adapts it to measured dispatch wall
        # time (so --checkpoint/--progress-every fire at useful
        # intervals, advisor r2) without recompiling
        key = (SC, FCap, AccCap, VC, CH)
        if key in self._res_cache:
            obs.current().counter("compile.cache_hits")
            return self._res_cache[key]
        obs.current().counter("compile.cache_misses")
        A, W, K, PW = self.A, self.W, self.K, self.PW
        plan = self.plan
        C = A * CH
        inv_fns = self.inv_fns
        con_fns = self.constraint_fns
        keys_of = self._keys_of
        expand = self._expand_fn()
        check_deadlock = self.model.check_deadlock
        assert FCap % CH == 0
        # device POR (ISSUE 18): the persistent-set filter probes the
        # PRE-LEVEL seen snapshot (chunk bodies close over level()'s
        # `seen` — the merge runs after all chunks), so the resident,
        # level and mesh engines make identical ample decisions and
        # produce identical reduced counts.  The three counters always
        # ride the carry/summary (zero when POR is off) so the host
        # unpack is unconditional.
        por_plan = self._por_plan() if self.por else None
        por = por_plan is not None
        if por:
            por_inst = jnp.asarray(por_plan["inst_arm"])
            por_safe_v = jnp.asarray(por_plan["arm_safe"])

        def level(seen, seen_count, frontier, fcount):
            # frontier is PACKED [FCap, PW]; each chunk unpacks to lanes
            # right before expansion — the carry (and HBM residency) stay
            # at the packed width
            nchunks = (fcount + CH - 1) // CH

            def chunk_body(carry):
                (ci, acc_keys, acc_rows, acc_n, gen, stat,
                 bad_row, ovcode, pora, porx, porm) = carry
                base = ci * CH
                chunk_p = lax.dynamic_slice(frontier, (base, 0),
                                            (CH, PW))
                chunk = plan.unpack_rows(chunk_p)
                fvalid = (jnp.arange(CH) + base) < fcount
                en, aok, ov, succ = expand(chunk)
                valid = en & fvalid[None, :]
                gen = gen + jnp.sum(valid, dtype=jnp.int32)

                # lane-capacity overflow inside an enabled action: abort.
                # The max OV_* CODE rides along so the host can tell a
                # compile-recovery demotion (OV_DEMOTED — raise no caps,
                # run host_seen) from a real capacity overflow
                ov_codes = jnp.where(fvalid[None, :], ov, 0)
                ovf_lanes = jnp.any(ov_codes != 0)
                ovcode = jnp.maximum(ovcode,
                                     jnp.max(ov_codes).astype(jnp.int32))
                # Assert(FALSE) inside an enabled action
                abad = (~aok) & fvalid[None, :]
                assert_any = jnp.any(abad)
                a_f = jnp.argmax(abad.reshape(-1)) % CH
                # deadlock: a frontier state with no enabled action at all
                dead = fvalid & ~jnp.any(en, axis=0)
                dead_any = check_deadlock & jnp.any(dead)
                d_f = jnp.argmax(dead)

                cand = succ.reshape(C, W)
                cvalid = valid.reshape(C)
                vcnt = jnp.sum(cvalid, dtype=jnp.int32)
                # compact valid candidates to a VC-bounded block before
                # hashing: ~95% of the dense (state x action) grid is
                # disabled, so hashing only the survivors is the win
                ops = ((1 - cvalid.astype(jnp.int32)),
                       jnp.arange(C, dtype=jnp.int32))
                comp = lax.sort(ops, num_keys=1, is_stable=True)
                cidx = comp[1][:VC]
                rows_cu = jnp.take(cand, jnp.clip(cidx, 0, C - 1),
                                   axis=0)
                vmask = jnp.arange(VC) < vcnt
                rows_cu = jnp.where(vmask[:, None], rows_cu, SENTINEL)
                keys_c, rows_c, pack_ovf = keys_of(rows_cu, vmask)
                # pack-guard overflow aborts exactly like a lane
                # overflow (OV_PACK: the host names JAXMC_PACK=0);
                # kernel codes (esp. OV_DEMOTED) keep priority so the
                # hybrid demote-restart advice survives
                ovf_lanes = ovf_lanes | pack_ovf
                ovcode = jnp.where(
                    ovcode == 0,
                    jnp.where(pack_ovf, OV_PACK, 0).astype(jnp.int32),
                    ovcode)

                if por:
                    # persistent-set filter (ISSUE 18): probe the
                    # compacted candidate keys against the pre-level
                    # seen prefix, scatter the verdicts back onto the
                    # dense [A, CH] grid, mask every non-ample arm's
                    # candidates.  Deadlock/assert above read PRE-mask
                    # enabledness; gen drops to the reduced stream.
                    found_c, _ = _seen_probe(seen, seen_count, keys_c,
                                             SC)
                    found_g = jnp.zeros(C, dtype=bool).at[cidx].set(
                        found_c & vmask, mode="drop",
                        unique_indices=True)
                    keep_g, n_amp, n_exp = _por_mask(
                        found_g, cvalid, por_inst, por_safe_v, A, CH)
                    keep_c = jnp.take(keep_g, jnp.clip(cidx, 0, C - 1)) \
                        & vmask
                    n_masked = jnp.sum(vmask & ~keep_c,
                                       dtype=jnp.int32)
                    inv_key = jnp.concatenate([
                        jnp.ones((VC, 1), jnp.int32),
                        jnp.full((VC, K - 1), SENTINEL, jnp.int32)],
                        axis=1)
                    keys_c = jnp.where(keep_c[:, None], keys_c, inv_key)
                    rows_c = jnp.where(keep_c[:, None], rows_c, SENTINEL)
                    gen = gen - n_masked
                    pora = pora + n_amp
                    porx = porx + n_exp
                    porm = porm + n_masked

                # append the block at acc_n (clamped; overflow redoes the
                # level so clobbered rows never count)
                off = jnp.clip(acc_n, 0, AccCap - VC)
                acc_keys = lax.dynamic_update_slice(acc_keys, keys_c,
                                                    (off, 0))
                acc_rows = lax.dynamic_update_slice(acc_rows, rows_c,
                                                    (off, 0))
                acc_n = acc_n + vcnt

                stat = jnp.where(
                    stat != ST_CONTINUE, stat,
                    jnp.where(
                        ovf_lanes, ST_OVF_LANES,
                        jnp.where(
                            vcnt > VC, ST_OVF_VC,
                            jnp.where(acc_n + VC > AccCap, ST_OVF_ACC,
                                      ST_CONTINUE))))
                # stat is still CONTINUE iff no earlier chunk reported
                # anything, so this is the first detection
                first_bad = (stat == ST_CONTINUE) & \
                    (assert_any | dead_any)
                bad_f = jnp.where(assert_any, a_f, d_f)
                brow = lax.dynamic_slice(frontier,
                                         (base + bad_f.astype(jnp.int32), 0),
                                         (1, PW))[0]
                bad_row = jnp.where(first_bad, brow, bad_row)
                stat = jnp.where(
                    (stat == ST_CONTINUE) & assert_any, ST_ASSERT,
                    jnp.where((stat == ST_CONTINUE) & dead_any,
                              ST_DEADLOCK, stat))
                return (ci + 1, acc_keys, acc_rows, acc_n, gen, stat,
                        bad_row, ovcode, pora, porx, porm)

            def chunk_cond(carry):
                # stop at the FIRST non-continue status: carrying on after
                # an assert/deadlock would skip the accumulator-overflow
                # checks (they only arm while stat == CONTINUE) and let
                # clamped writes clobber earlier candidate blocks
                ci, _, _, _, _, stat, _, _, _, _, _ = carry
                return (ci < nchunks) & (stat == ST_CONTINUE)

            acc_keys0 = jnp.full((AccCap, K), SENTINEL, jnp.int32)
            acc_rows0 = jnp.full((AccCap, PW), SENTINEL, jnp.int32)
            bad_row0 = jnp.full((PW,), SENTINEL, jnp.int32)
            (_, acc_keys, acc_rows, acc_n, gen, stat, bad_row,
             ovcode, pora, porx, porm) = \
                lax.while_loop(chunk_cond, chunk_body,
                               (jnp.int32(0), acc_keys0, acc_rows0,
                                jnp.int32(0), jnp.int32(0),
                                jnp.int32(ST_CONTINUE), bad_row0,
                                jnp.int32(0), jnp.int32(0),
                                jnp.int32(0), jnp.int32(0)))

            # conservative seen-capacity check BEFORE the merge: every
            # accumulated candidate could be new
            stat = jnp.where((stat == ST_CONTINUE) &
                             (seen_count + acc_n > SC), ST_OVF_SEEN, stat)

            # ---- merge-dedup the level's candidates against seen ----
            # The shared O(new) rank-merge core (_rank_merge, also the
            # mesh engine's merge strategy): the candidate block is
            # sorted by chained STABLE single-key passes and the
            # seen-set is never re-sorted — new keys merge by rank (two
            # vectorized binary searches + scatters), so the sort work
            # is O(new), not O(seen), per level.
            rm = _rank_merge(seen, seen_count, acc_keys, AccCap, SC, K)
            new_count = rm["new_count"]
            nvalid = jnp.arange(AccCap) < new_count
            new_rows = jnp.take(acc_rows,
                                jnp.clip(rm["nk_sidx"], 0, AccCap - 1),
                                axis=0)
            new_rows = jnp.where(nvalid[:, None], new_rows, SENTINEL)
            seen2 = rm["seen2"]
            seen_count2 = rm["seen_count2"]

            # constraints: violating states stay fingerprinted in seen2
            # but are discarded (not distinct / checked / explored).
            # new_rows are PACKED; the predicate kernels read lanes
            new_rows_u = plan.unpack_rows(new_rows) \
                if (con_fns or inv_fns) else new_rows
            explore = nvalid
            for nm, f in con_fns:
                explore = explore & jax.vmap(f)(new_rows_u)
            explore_count = jnp.sum(explore, dtype=jnp.int32)
            stat = jnp.where((stat == ST_CONTINUE) &
                             (explore_count > FCap), ST_OVF_FRONT, stat)

            idx4 = jnp.arange(AccCap, dtype=jnp.int32)
            ops4 = ((1 - explore.astype(jnp.int32)), idx4)
            comp4 = lax.sort(ops4, num_keys=1, is_stable=True)
            fidx = comp4[1][:FCap]
            front_rows = jnp.take(new_rows,
                                  jnp.clip(fidx, 0, AccCap - 1), axis=0)
            frontvalid = jnp.arange(FCap) < explore_count
            front_rows = jnp.where(frontvalid[:, None], front_rows,
                                   SENTINEL)

            inv_bad_any = jnp.asarray(False)
            inv_bad_idx = jnp.asarray(0, jnp.int32)
            inv_bad_which = jnp.asarray(-1, jnp.int32)
            front_rows_u = plan.unpack_rows(front_rows) if inv_fns \
                else front_rows
            for wi, (nm, f) in enumerate(inv_fns):
                ok = jax.vmap(f)(front_rows_u)
                bad = frontvalid & ~ok
                any_ = jnp.any(bad)
                idx = jnp.argmax(bad).astype(jnp.int32)
                first = jnp.logical_and(any_, ~inv_bad_any)
                inv_bad_idx = jnp.where(first, idx, inv_bad_idx)
                inv_bad_which = jnp.where(first, wi, inv_bad_which)
                inv_bad_any = inv_bad_any | any_
            inv_row = lax.dynamic_slice(front_rows, (inv_bad_idx, 0),
                                        (1, PW))[0]
            bad_row = jnp.where(inv_bad_any & (stat == ST_CONTINUE),
                                inv_row, bad_row)
            stat = jnp.where((stat == ST_CONTINUE) & inv_bad_any,
                             ST_INV, stat)

            return (seen2, seen_count2, front_rows, explore_count, gen,
                    explore_count, stat, inv_bad_which, bad_row, ovcode,
                    pora, porx, porm)

        def run(seen, seen_count, frontier, fcount, distinct,
                gen_lo, gen_hi, depth, max_states, maxlvl):
            def cond(carry):
                (_, _, _, _, _, _, _, _, lvls, stat, _, _, _,
                 _, _, _) = carry
                return (stat == ST_CONTINUE) & (lvls < maxlvl)

            def body(carry):
                (seen, seen_count, frontier, fcount, distinct,
                 gen_lo, gen_hi, depth, lvls, stat, which, brow,
                 ovcode, pora, porx, porm) = carry
                (seen2, seen_count2, front2, fcount2, gen_l, kept,
                 lstat, lwhich, lbrow, lovcode, lpora, lporx,
                 lporm) = level(seen, seen_count, frontier, fcount)
                ovf = (lstat == ST_OVF_SEEN) | (lstat == ST_OVF_FRONT) | \
                    (lstat == ST_OVF_ACC) | (lstat == ST_OVF_VC) | \
                    (lstat == ST_OVF_LANES)
                # overflow rolls the whole level back (growable caps are
                # redone after growth; lane overflow aborts with the
                # last completed level's exact counts)
                seen2 = jnp.where(ovf, seen, seen2)
                seen_count2 = jnp.where(ovf, seen_count, seen_count2)
                front2 = jnp.where(ovf, frontier, front2)
                fcount2 = jnp.where(ovf, fcount, fcount2)
                distinct2 = jnp.where(ovf, distinct, distinct + kept)
                lo = (gen_lo.astype(jnp.uint32) +
                      gen_l.astype(jnp.uint32))
                wrapped = lo < gen_lo.astype(jnp.uint32)
                gen_lo2 = jnp.where(ovf, gen_lo, lo.astype(jnp.int32))
                gen_hi2 = jnp.where(ovf, gen_hi,
                                    gen_hi + wrapped.astype(jnp.int32))
                # deadlock/assert states belong to the CURRENT frontier
                # (depth d), unlike invariant violations which live in
                # the newly found level (d+1) — don't advance depth for
                # them, matching the interp/level/host_seen backends
                keep_depth = ovf | (lstat == ST_DEADLOCK) | \
                    (lstat == ST_ASSERT)
                depth2 = jnp.where(keep_depth, depth, depth + 1)
                stat2 = jnp.where(
                    lstat != ST_CONTINUE, lstat,
                    jnp.where(fcount2 == 0, ST_DONE,
                              jnp.where((max_states > 0) &
                                        (distinct2 >= max_states),
                                        ST_TRUNC, ST_CONTINUE)))
                # POR counters roll back with the level: a redone level
                # must not count its ample decisions twice
                pora2 = jnp.where(ovf, pora, pora + lpora)
                porx2 = jnp.where(ovf, porx, porx + lporx)
                porm2 = jnp.where(ovf, porm, porm + lporm)
                return (seen2, seen_count2, front2, fcount2, distinct2,
                        gen_lo2, gen_hi2, depth2, lvls + 1, stat2,
                        jnp.where(lstat == ST_INV, lwhich, which), lbrow,
                        jnp.where(lstat == ST_OVF_LANES, lovcode,
                                  ovcode), pora2, porx2, porm2)

            carry0 = (seen, seen_count, frontier, fcount, distinct,
                      gen_lo, gen_hi, depth, jnp.int32(0),
                      jnp.int32(ST_CONTINUE), jnp.int32(-1),
                      jnp.full((PW,), SENTINEL, jnp.int32),
                      jnp.int32(0), jnp.int32(0), jnp.int32(0),
                      jnp.int32(0))
            (seen, seen_count, frontier, fcount, distinct, gen_lo,
             gen_hi, depth, _, stat, which, brow, ovcode, pora, porx,
             porm) = \
                lax.while_loop(cond, body, carry0)
            # indices 0-8 are the PR-6 summary; 9-11 are the per-
            # dispatch POR counters (ISSUE 18; zero when POR is off)
            summary = jnp.stack([stat, seen_count, fcount, distinct,
                                 gen_lo, gen_hi, depth, which, ovcode,
                                 pora, porx, porm])
            return seen, frontier, summary, brow

        # DONATED dispatch (ISSUE 6): the seen table (arg 0) and the
        # packed frontier (arg 2) — the two big device buffers — update
        # in place across dispatches instead of copying per batch
        donate = (0, 2) if self.donate else ()
        jitted = obs.prof_wrap("bfs.resident_run", jax.jit(
            run, static_argnames=(), donate_argnums=donate))
        self._res_cache[key] = jitted
        return jitted


    def _save_caps_profile(self, caps: Dict[str, int],
                           variant: str = "",
                           keys: Optional[Tuple[str, ...]] = None,
                           optional: Tuple[str, ...] = ()
                           ) -> None:
        """Persist the capacity profile a finished resident search ended
        with (ISSUE 6): the next resident run on this (module, layout)
        starts at these caps, so its warm-up compile covers the whole
        run and `window_recompiles` reads 0.  Best-effort: a profile is
        a hint, never allowed to fail a successful run.  `variant`/
        `keys` let engine families persist their own cap shapes (the
        mesh engine stores one profile per device count + exchange
        strategy, ISSUE 8)."""
        if not self.cap_profile:
            return
        try:
            from ..compile.cache import save_capacity_profile
            # profiles are NAMESPACED by backend platform (ISSUE 11):
            # the default (resident single-chip) variant is the
            # descriptor's namespace; engine families (mesh) pass their
            # own pre-namespaced variant + key shape
            kw = dict(chunk=int(self.chunk),
                      variant=self.backend_desc.profile_variant())
            if keys is not None:
                kw = dict(variant=variant, keys=keys, optional=optional)
            elif optional:
                kw["optional"] = optional
            path = save_capacity_profile(
                self.model.module.name, self._layout_sig(), dict(caps),
                **kw)
            if path:
                self.log(f"-- capacity profile saved to {path}")
        except Exception:  # noqa: BLE001 — hints never break runs
            pass

    def _pack_ovf_msg(self) -> str:
        return ("a value escaped its bit-packed lane's profiled range "
                "(compile/pack.py profiles raw-int lanes from sampled "
                "states with a 3x margin): deepen --sample or rerun "
                "with JAXMC_PACK=0 (unpacked lanes) — counts stay exact "
                "either way")

    def _caps_note(self) -> str:
        """Which variable uses which bounded lane capacity — shown in
        capacity-overflow violations so the user knows WHAT to raise
        (the r3 MCraft_3s debugging pain: 'a container overflowed' with
        no name). Renders inside error paths — never allowed to raise."""
        try:
            return self._caps_note_inner()
        except Exception:  # noqa: BLE001 — diagnostics must not mask
            return "raise --seq-cap/--grow-cap/--kv-cap"

    def _caps_note_inner(self) -> str:
        parts: Dict[str, None] = {}  # ordered dedupe (fcn repeats keys)

        def walk(spec, path):
            k = spec.kind
            if k in ("seq", "growset", "kvtable"):
                flag = {"seq": "--seq-cap", "growset": "--grow-cap",
                        "kvtable": "--kv-cap"}[k]
                parts.setdefault(f"{path}:{k}[cap {spec.cap}, {flag}]")
            for sub in (spec.elems or ()):
                walk(sub, path)
            for sub in (spec.elem, spec.val):
                if sub is not None:
                    walk(sub, path)
            for _fields, fspecs in (spec.variants or ()):
                for sub in fspecs:
                    walk(sub, path)

        for v in self.layout.vars:
            walk(self.layout.specs[v], v)
        return "; ".join(parts) if parts else "no bounded containers"

    def _prepare_init(self, t0, warnings):
        """Shared init-state preparation for every device search mode:
        encode + dedup the enumerated init states, run the init-state
        invariant/refinement checks, log the TLC-format init line.

        Returns (init_rows, explored_init, n_init, err): err is a
        ready-to-return CheckResult when an initial state violates an
        invariant or a refinement's initial predicate, else None.

        The clean-path result is deterministic per engine, so it is
        memoized: repeated run() calls (bench warm-up + timed re-runs)
        skip the re-encode/canon/view work."""
        cached = getattr(self, "_init_prep", None)
        if cached is not None:
            return cached + (None,)
        layout = self.layout
        raw = [layout.encode(st) for st in self.init_states]
        if raw and self.canon_fn is not None:
            # cfg SYMMETRY: dedup/count init states by their orbit's
            # canonical representative, matching the interp's add_state
            # (which canonicalizes BEFORE the seen probe). Without this,
            # distinct init states sharing an orbit would inflate the
            # device counts and seed `seen` with duplicate canonical
            # fingerprints, breaking the sorted-unique invariant the
            # resident rank-merge relies on.
            raw = list(np.asarray(self.canon_fn(np.stack(raw))))
        if raw and self.view_fn is not None:
            # cfg VIEW: init states sharing a view value count ONCE
            # (TLC fingerprints the view) — keep the first state per key
            kb = np.asarray(jax.vmap(self.view_fn)(
                jnp.asarray(np.stack(raw))))
            if kb.ndim == 1:
                kb = kb[:, None]
            rows: Dict[bytes, np.ndarray] = {}
            for i, rr in enumerate(raw):
                rows.setdefault(np.ascontiguousarray(kb[i]).tobytes(),
                                np.asarray(rr, np.int32))
            init_rows = np.stack(list(rows.values()))
        else:
            rows = {}
            for rr in raw:
                rows[np.asarray(rr, np.int32).tobytes()] = True
            init_rows = np.stack([np.frombuffer(kk, dtype=np.int32)
                                  for kk in rows.keys()]) \
                if rows else np.zeros((0, self.W), np.int32)
        n_init = len(init_rows)
        explored_init, init_viol = filter_init_states(self.model, layout,
                                                      init_rows)
        if init_viol is not None:
            nm, st = init_viol
            return init_rows, explored_init, n_init, self._mk_result(
                False, len(explored_init) + 1, n_init, 0, t0, warnings,
                Violation("invariant", nm, [(st, "Initial predicate")]))
        rv = self._refine_init(init_rows, explored_init)
        if rv is not None:
            nm, st = rv
            return init_rows, explored_init, n_init, self._mk_result(
                False, len(explored_init), n_init, 0, t0, warnings,
                Violation("property", nm, [(st, "Initial predicate")],
                          f"initial state violates {nm}'s initial "
                          f"predicate"))
        distinct = len(explored_init)
        self.log(f"Finished computing initial states: {distinct} distinct "
                 f"state{'s' if distinct != 1 else ''} generated.")
        self._init_prep = (init_rows, explored_init, n_init)
        return init_rows, explored_init, n_init, None

    # ---- checkpoint/resume (device backends) ----
    #
    # TLC checkpoints long runs to states/ (SURVEY.md §5, testout1:10);
    # the interp engine mirrors that with --checkpoint/--resume. The
    # device modes checkpoint BETWEEN levels (level and host_seen modes)
    # or between dispatches (resident mode), so a checkpoint is always a
    # consistent level boundary and resumed full-run counts stay exact.

    def _layout_sig(self) -> str:
        """Fingerprint of the lane encoding: a resume is only sound when
        the resuming process rebuilds the IDENTICAL layout (layout
        construction is deterministic for a given model + Bounds — BFS
        prefix sampling, no RNG)."""
        import hashlib
        lay = self.layout
        # the lane PLAN rides in the signature: checkpointed rows are
        # stored packed, so a resume must rebuild the identical packing
        # (it does: the plan derives deterministically from the same
        # sampling; JAXMC_PACK toggles change the signature on purpose)
        desc = repr((lay.vars, [lay.specs[v] for v in lay.vars],
                     [str(v) for v in lay.uni.values],
                     lay.plan.signature()))
        return hashlib.sha256(desc.encode()).hexdigest()

    def _write_ck(self, mode: str, **state) -> None:
        # checksummed + schema-versioned container (engine/ckpt.py):
        # resume refuses truncated/corrupt/mismatched files with a
        # one-line CkptError instead of unpickling garbage
        from ..engine import ckpt as _ckpt
        payload = dict(mode=mode, module=self.model.module.name,
                       vars=list(self.model.vars),
                       layout_sig=self._layout_sig(), **state)
        if self._tiers is not None and self._tiers.active:
            # the FULL tier hierarchy rides every checkpoint (ISSUE 12):
            # kill/resume mid-spill restores host and disk runs, so the
            # resumed dedup set is exactly the crashed run's
            payload["tiers"] = self._tiers.dump()
        try:
            with obs.current().span("checkpoint.write", mode=mode):
                _ckpt.write_checkpoint(
                    self.checkpoint_path, "device",
                    {"module": self.model.module.name, "mode": mode},
                    payload)
        except _ckpt.CkptError as ex:
            # a failed periodic write must not kill the search: keep
            # running on the previous checkpoint
            obs.current().counter("checkpoint.write_failures")
            self.log(f"WARNING: checkpoint write failed ({ex}); the run "
                     f"continues on the previous checkpoint")
            return
        self.log(f"Checkpointing run to {self.checkpoint_path}")

    def _load_ck(self, mode: str) -> dict:
        from ..engine.ckpt import CkptError, load_checkpoint
        _, ck = load_checkpoint(self.resume_from, kind="device")
        if ck.get("module") != self.model.module.name or \
                ck.get("vars") != list(self.model.vars):
            raise CkptError(
                f"cannot resume: checkpoint is for module "
                f"{ck.get('module')!r} with variables {ck.get('vars')}, "
                f"not {self.model.module.name!r}")
        if ck.get("mode") != mode:
            raise CkptError(
                f"cannot resume: checkpoint was written by the "
                f"{ck.get('mode')!r} device mode, this run uses {mode!r} "
                f"(re-run with the matching flags)")
        if ck.get("layout_sig") != self._layout_sig():
            raise CkptError(
                "cannot resume: the lane layout differs from the "
                "checkpoint's (different --seq-cap/--grow-cap/--kv-cap "
                "or a changed model?)")
        if ck.get("tiers") is not None:
            # restore the cold tiers BEFORE any step compiles, so the
            # resumed engine probes (and its steps stream keys) from
            # the first level on
            self._ensure_tiers().load(ck["tiers"])
        return ck

    def _restore_ck_state(self, ck, graph):
        """Shared level/host_seen resume restore: validates trace and
        behavior-graph compatibility with THIS run's needs, then returns
        (distinct, generated, depth, trace_levels, frontier_maps, graph,
        frontier_sids) — the trace pair is None when store_trace is
        off."""
        if self.store_trace and ck.get("trace_levels") is None:
            raise ValueError(
                "cannot resume with traces: the checkpoint was written "
                "with --no-trace")
        frontier_sids = None
        if graph is not None:
            ckg = ck.get("graph")
            if ckg is None:
                raise ValueError(
                    "cannot resume with temporal properties: the "
                    "checkpoint has no behavior graph")
            if graph.collect_edges and not ckg.collect_edges:
                # mirror engine/explore.py's interp-resume guard: an
                # edge log cannot be reconstructed after the fact
                raise ValueError(
                    "cannot resume with this PROPERTY set: the "
                    "checkpoint's behavior graph has no edge log (it "
                    "was written for 'always'-form obligations only)")
            graph = ckg
            frontier_sids = ck["frontier_sids"]
        trace_levels = ck["trace_levels"] if self.store_trace else None
        frontier_maps = ck["frontier_maps"] if self.store_trace else None
        self.log(f"Resumed from {self.resume_from}: {ck['distinct']} "
                 f"distinct states, {len(ck['frontier'])} on queue.")
        return (ck["distinct"], ck["generated"], ck["depth"],
                trace_levels, frontier_maps, graph, frontier_sids)

    def _ck_state_kwargs(self, distinct, generated, depth, trace_levels,
                         frontier_maps, graph, frontier_sids):
        """Shared level/host_seen checkpoint payload fields."""
        return dict(
            distinct=distinct, generated=generated, depth=depth,
            trace_levels=trace_levels if self.store_trace else None,
            frontier_maps=frontier_maps if self.store_trace else None,
            graph=graph, frontier_sids=frontier_sids)

    def _write_host_snapshot(self, trace_levels, frontier_maps, graph,
                             depth, generated) -> None:
        """Demotion snapshot: an INTERP-format checkpoint (engine/ckpt.py
        payload, `<checkpoint>.host`) rebuilt from the host-side trace
        levels, so when the device path dies terminally the parallel CPU
        engine resumes from the last level barrier instead of restarting
        from scratch (cli.py owns the fallback).

        Exactness: every kept state of every level is decoded and
        re-fingerprinted with the interp's own state_fingerprint, so the
        resumed dedup set is exact.  Constraint-DISCARDED fingerprints
        are not reconstructible from rows the device never kept — their
        absence is count-equivalent: the resumed engine re-generates and
        re-discards such a state on first contact, exactly what the
        serial engine counts.  Skipped (with one log line) when traces
        are off (--no-trace), in resident mode (no host rows), or when
        cfg SYMMETRY ran UNREDUCED on the device (the interp would
        reduce, so the carried counts would not be comparable)."""
        if not self.store_trace or not self.checkpoint_path:
            return
        if self.model.symmetry is not None and self.canon_fn is None \
                and not self.sym_identity:
            # identity groups excepted: the interp reduces them to the
            # same (unreduced) partition, so the snapshot stays exact
            if not getattr(self, "_host_snap_skip_logged", False):
                self._host_snap_skip_logged = True
                self.log("-- no host snapshot: SYMMETRY ran unreduced on "
                         "the device (interp counts would differ)")
            return
        from ..engine import ckpt as _ckpt
        from ..engine.explore import make_canonicalizer, state_fingerprint
        model = self.model
        vars = model.vars
        canon = make_canonicalizer(model)
        view_expr = getattr(model, "view", None)  # None on device paths
        states: List[Dict[str, Any]] = []
        parents: List[Optional[int]] = []
        labels: List[str] = []
        depth_of: List[int] = []
        seen: Dict[Any, int] = {}
        level_sids: List[List[int]] = []
        for lvl, (rows, prov, par_div) in enumerate(trace_levels):
            sids: List[int] = []
            for ridx in frontier_maps[lvl]:
                ridx = int(ridx)
                st = self.layout.decode_packed(np.asarray(rows[ridx]))
                sid = len(states)
                if prov is None:
                    parents.append(None)
                    labels.append("Initial predicate")
                else:
                    p = int(prov[ridx])
                    a, pf = p // par_div, p % par_div
                    parents.append(level_sids[lvl - 1][pf])
                    labels.append(self.labels_flat[a])
                states.append(st)
                depth_of.append(lvl)
                key = state_fingerprint(model, canon, view_expr, vars, st)
                # an fp128 collision may have collapsed two interp-
                # distinct states device-side; keep the first sid — the
                # resumed run stays exact going forward
                seen.setdefault(key, sid)
                sids.append(sid)
            level_sids.append(sids)
        collect_edges = graph is not None and graph.collect_edges
        payload = _ckpt.interp_payload(
            model, vars, states, parents, labels, depth_of,
            level_sids[-1] if level_sids else [], generated,
            max(depth - 1, 0), seen,
            graph.edges if collect_edges else None, collect_edges, [])
        snap = self.checkpoint_path + ".host"
        try:
            with obs.current().span("checkpoint.host_snapshot",
                                    states=len(states)):
                _ckpt.write_checkpoint(
                    snap, "interp",
                    {"module": model.module.name,
                     "engine": "device-snapshot"},
                    payload)
        except _ckpt.CkptError as ex:
            obs.current().counter("checkpoint.write_failures")
            self.log(f"WARNING: host snapshot write failed ({ex}); the "
                     f"run continues on the previous snapshot")
            return
        obs.current().counter("checkpoint.host_snapshots")
        self.log(f"Host snapshot (CPU-resumable) written to {snap}")

    def _run_resident(self) -> CheckResult:
        t0 = time.time()
        tel = obs.current()
        layout = self.layout
        W, K = self.W, self.K
        warnings = ["resident mode: search runs device-side end to end; "
                    "no counterexample traces (rerun with the level/"
                    "host_seen device modes or the interp for a trace)",
                    "resident mode (W={}): dedup on 128-bit fingerprints; "
                    "collision probability < n^2 * 2^-129".format(W)]
        warnings.extend(self._temporal_warnings())
        warnings.extend(self._symmetry_warnings())
        warnings.extend(self._por_warnings())

        init_rows, explored_init, n_init, err = \
            self._prepare_init(t0, warnings)
        if err is not None:
            return err
        generated = n_init
        distinct = len(explored_init)

        CH = _pow2_at_least(self.chunk, lo=64)
        # every overflow-growth costs a full XLA recompile (minutes on
        # the big while_loop program), while capacity is cheap device
        # memory (seen keys at SC=1<<20 are 20MB) - so on an accelerator
        # start generous; on CPU (tests) stay small to keep compiles fast
        on_accel = jax.devices()[0].platform != "cpu"
        if self._res_caps is not None:
            caps = self._res_caps
        elif self._res_caps_hint:
            # caller-supplied steady-state caps (the corpus manifest's
            # res_caps record, bench.py's bench-model sizes, or a
            # persisted capacity profile) are the BASE, not a floor
            # merged into the platform defaults: a small model's hint
            # must be allowed to SHRINK the buckets (the capacity-sized
            # sorts/gathers inside the level step are exactly what made
            # the r04 kernel lose to the interpreter on small models).
            # A wrong hint only costs an overflow-growth recompile.
            h = self._res_caps_hint
            caps = {
                "SC": _pow2_at_least(int(h.get("SC", 1)), lo=256),
                "FCap": _pow2_at_least(int(h.get("FCap", 1)), lo=64),
                "AccCap": _pow2_at_least(int(h.get("AccCap", 1)),
                                         lo=128),
                "VC": _pow2_at_least(int(h.get("VC", 1)), lo=64)}
        else:
            caps = ({"SC": 1 << 20, "FCap": max(1 << 16, CH),
                     "AccCap": 1 << 17, "VC": 1 << 14} if on_accel else {
                "SC": _pow2_at_least(max(4 * n_init, 1), lo=1 << 15),
                "FCap": CH, "AccCap": 1 << 15, "VC": 1 << 13})
        # a device seen cap (ISSUE 12) bounds the hot tier from the
        # start: defaults/hints/profiles above it would keep the run
        # from ever spilling (the floors below may still soft-breach a
        # cap too small to seat the init keys)
        if self.seen_cap is not None:
            caps["SC"] = min(caps["SC"], self.seen_cap)
        # floors no hint may undercut: the seen table must seat every
        # init key and the frontier every init row (a 256-cap hint on a
        # 1600-init model would otherwise crash the seeding, not grow)
        caps["SC"] = max(caps["SC"],
                         _pow2_at_least(max(4 * n_init, 1), lo=256))
        caps["FCap"] = max(caps["FCap"], _pow2_at_least(max(n_init, 1),
                                                        lo=CH))
        # VC can never usefully exceed the dense candidate-grid size
        # A*CH (and must not: [:VC] slices of C-row arrays assume VC<=C);
        # AccCap must cover both one VC block past acc_n and the [:FCap]
        # slice of the accumulator taken for the next frontier
        caps["VC"] = min(caps["VC"], self.A * CH)
        caps["AccCap"] = max(caps["AccCap"], 2 * caps["VC"], caps["FCap"])
        # HBM model (ISSUE 17): the finalized caps ARE the device
        # buffers the resident loop carries — register them so the
        # profiler's hbm_peak_bytes watermark tracks cap growth
        obs.note_buffer("resident.seen", caps["SC"] * self.K * 4)
        obs.note_buffer("resident.frontier", caps["FCap"] * self.PW * 4)
        obs.note_buffer("resident.accumulator",
                        caps["AccCap"] * (self.K + self.PW) * 4)
        obs.note_buffer("resident.candidates",
                        caps["VC"] * (self.K + self.PW) * 4)
        # levels per dispatch: the host only sees status (and can only
        # checkpoint / log progress) between dispatches, so maxlvl adapts
        # to measured dispatch wall time — targeting the tighter of
        # progress_every/checkpoint_every — instead of a fixed 64 that
        # could run for hours on a large model (advisor r2)
        # start SMALL and double up: the first dispatches are the ones
        # with no timing evidence, and a 64-level opener on a big model
        # could run for hours before the host could checkpoint or log
        # progress (review r3) — a few extra cheap dispatches at the
        # start cost almost nothing
        # ...unless a PREVIOUS run on this engine already learned the
        # model's depth/dispatch timing: warm re-runs (bench timed
        # windows) then cover the whole search in as few dispatches as
        # the adaptive controller settled on, instead of re-ramping
        # 4 -> 8 -> 16 every run
        maxlvl = min(getattr(self, "_res_maxlvl_warm", 4),
                     self._res_maxlvl)
        target_s = max(1.0, min(
            self.progress_every or 30.0,
            (self.checkpoint_every or 1e9) if self.checkpoint_path
            else 1e9))

        # packed init boundary: keys + packed rows in one pass; a pack
        # overflow at init is an observation gap (abort exactly)
        init_keys, init_packed, init_povf = self._host_keys(init_rows)
        if init_povf:
            return self._mk_result(
                False, distinct, generated, 0, t0, warnings,
                Violation("error", "capacity overflow", [],
                          self._pack_ovf_msg()))
        frontier = np.full((caps["FCap"], self.PW), SENTINEL, np.int32)
        frontier[:distinct] = init_packed[explored_init]
        frontier = jnp.asarray(frontier)
        fcount = distinct

        seen = np.full((caps["SC"], K), SENTINEL, np.int32)
        if n_init:
            order = np.lexsort(tuple(init_keys[:, i]
                                     for i in reversed(range(K))))
            seen[:n_init] = init_keys[order]
        seen = jnp.asarray(seen)
        seen_count = n_init

        depth = 0
        if self.resume_from:
            ck = self._load_ck("resident")
            for kk in caps:
                caps[kk] = max(caps[kk], ck.get("caps", {}).get(kk, 0))
            # re-apply the cap invariants: the checkpointing run may have
            # used a different --chunk, and VC must never exceed A*CH
            caps["VC"] = min(caps["VC"], self.A * CH)
            caps["AccCap"] = max(caps["AccCap"], 2 * caps["VC"],
                                 caps["FCap"])
            cs, fr = ck["seen"], ck["frontier"]
            seen_np = np.full((caps["SC"], K), SENTINEL, np.int32)
            seen_np[:len(cs)] = cs
            seen = jnp.asarray(seen_np)
            seen_count = len(cs)
            fr_np = np.full((caps["FCap"], self.PW), SENTINEL, np.int32)
            fr_np[:len(fr)] = fr
            frontier = jnp.asarray(fr_np)
            fcount = len(fr)
            distinct = ck["distinct"]
            generated = ck["generated"]
            depth = ck["depth"]
            self.log(f"Resumed from {self.resume_from}: {distinct} "
                     f"distinct states, {fcount} on queue.")
            if fcount == 0:
                # a COMPLETED-run checkpoint (final_checkpoint, the
                # serve daemon's warm-resume source): nothing left to
                # explore — replay the stored verdict with ZERO kernel
                # dispatches (and therefore zero window recompiles)
                self.log("Model checking completed. No error has been "
                         "found.")
                self.log(f"{generated} states generated, {distinct} "
                         f"distinct states found, 0 states left on "
                         f"queue.")
                self.log(f"The depth of the complete state graph search "
                         f"is {depth}.")
                if self.checkpoint_path and self.final_checkpoint and \
                        self.checkpoint_path != self.resume_from:
                    self._write_ck(
                        "resident", caps=dict(caps),
                        seen=np.asarray(seen[:seen_count]),
                        frontier=np.zeros((0, self.PW), np.int32),
                        distinct=distinct, generated=generated,
                        depth=depth)
                return self._mk_result(True, distinct, generated,
                                       depth - 1, t0, warnings)

        max_states = jnp.int32(self.max_states or 0)
        gen_lo = int(np.int32(np.uint32(generated & 0xFFFFFFFF)))
        gen_hi = generated >> 32
        state = (seen, jnp.int32(seen_count), frontier, jnp.int32(fcount),
                 jnp.int32(distinct), jnp.int32(gen_lo), jnp.int32(gen_hi),
                 jnp.int32(depth))
        grow_flag = {ST_OVF_SEEN: "SC", ST_OVF_FRONT: "FCap",
                     ST_OVF_ACC: "AccCap", ST_OVF_VC: "VC"}
        # first progress line immediately (ISSUE 2): short runs get at
        # least one record; same format as the interval lines below
        self.log(f"Progress({depth}): {generated} states generated, "
                 f"{distinct} distinct states found, "
                 f"{fcount} states left on queue."
                 f"{obs.eta_suffix(distinct)}")
        last_progress = last_ck = time.time()
        while True:
            # chaos sites: crash / device failure between dispatches
            # (the only host-attention points resident mode has)
            from .. import faults
            faults.kill_self("run_kill", level=depth, engine="resident")
            faults.inject("device_run_fail", level=depth)
            if self._drain_requested(warnings, "resident"):
                if self.checkpoint_path:
                    self._write_ck(
                        "resident", caps=dict(caps),
                        seen=np.asarray(seen[:seen_count]),
                        frontier=np.asarray(frontier[:fcount]),
                        distinct=distinct, generated=generated,
                        depth=depth)
                return self._mk_result(True, distinct, generated, depth,
                                       t0, warnings, None,
                                       truncated=True, drained=True)
            ck_key = (caps["SC"], caps["FCap"], caps["AccCap"],
                      caps["VC"], CH)
            fresh_compile = ck_key not in self._res_cache
            runf = self._get_resident_run(*ck_key)
            t_disp = time.time()
            # once the run has spilled (ISSUE 12), every level needs a
            # cold-tier probe at the host boundary: pin the dispatch to
            # ONE level so the host sees each committed frontier
            eff_maxlvl = 1 if (self._tiers is not None
                               and self._tiers.active) else maxlvl
            seen, frontier, summary, brow = runf(*state, max_states,
                                                 jnp.int32(eff_maxlvl))
            jax.block_until_ready(summary)
            disp_wall = time.time() - t_disp
            # adapt levels-per-dispatch toward the host-attention target;
            # a dispatch that just paid an XLA recompile (cap growth) is
            # not evidence about execution speed — skip it
            if fresh_compile:
                pass
            elif disp_wall > 1.5 * target_s and maxlvl > 1:
                maxlvl = max(1, maxlvl // 2)
            elif disp_wall < target_s / 4 and \
                    maxlvl < self._res_maxlvl:
                maxlvl = min(self._res_maxlvl, maxlvl * 2)
            summary = np.asarray(summary)
            fcount_in, gen_in, dist_in = fcount, generated, distinct
            stat = int(summary[0])
            seen_count = int(summary[1])
            fcount = int(summary[2])
            distinct = int(summary[3])
            generated = (int(np.uint32(summary[5])) << 32) | \
                int(np.uint32(summary[4]))
            depth = int(summary[6])
            which = int(summary[7])
            ovcode = int(summary[8])
            # per-dispatch POR deltas: run() zero-seeds them per
            # dispatch and rolls back overflowed levels, so summing
            # across dispatches (including redos) never double-counts
            self._por_stats["ample"] += int(summary[9])
            self._por_stats["expanded"] += int(summary[10])
            self._por_stats["masked"] += int(summary[11])
            # cold-tier filter (ISSUE 12): after a spill the device
            # table restarted empty, so a committed level's frontier
            # may hold rows whose keys live in the host/disk runs —
            # exactly the rows the uncapped table would have deduped.
            # Probe and drop them (order-preserving) before counts,
            # truncation decisions, or the next dispatch see them.
            # Rolled-back levels (grow statuses) keep their frontier —
            # it was already filtered when it was admitted.
            if self._tiers is not None and self._tiers.active and \
                    fcount > 0 and stat not in grow_flag and \
                    stat not in (ST_OVF_LANES, ST_DONE):
                fr_np = np.asarray(frontier[:fcount])
                keep = self._tier_keep_mask(fr_np)
                n_dup = int((~keep).sum())
                if n_dup:
                    kept_rows = np.ascontiguousarray(fr_np[keep])
                    distinct -= n_dup
                    fcount = len(kept_rows)
                    fr_full = np.full((int(frontier.shape[0]), self.PW),
                                      SENTINEL, np.int32)
                    fr_full[:fcount] = kept_rows
                    frontier = jnp.asarray(fr_full)
                if stat == ST_TRUNC and self.max_states and \
                        distinct < self.max_states:
                    stat = ST_CONTINUE  # phantom limit: dups un-counted
                if fcount == 0 and stat == ST_CONTINUE:
                    stat = ST_DONE  # the whole level was cold dups
                self._tiers.publish_gauges(seen_count)
            self._res_caps = dict(caps)
            # one record per DISPATCH (the host only sees level batches
            # in resident mode): `level` is the depth reached, so indices
            # stay monotone — equal across an overflow-redo dispatch.
            # frontier/generated/new keep the other paths' semantics:
            # frontier going IN, per-dispatch generated/new deltas (so
            # summing `generated` across records gives the run total)
            tel.level(depth, dispatch=True, frontier=fcount_in,
                      generated=generated - gen_in,
                      new=distinct - dist_in, distinct=distinct,
                      seen=seen_count, status=stat,
                      fresh_compile=fresh_compile,
                      wall_s=round(disp_wall, 6))
            self._fp_occupancy = seen_count

            if stat in grow_flag:
                what = grow_flag[stat]
                old = caps[what]
                if what == "SC" and self.seen_cap is not None and \
                        old >= self.seen_cap and seen_count > 0:
                    # device tier full (ISSUE 12): instead of growing
                    # past the cap, compact the sorted prefix out to
                    # the cold tiers, restart the device table empty,
                    # and redo the level (the rollback preserved the
                    # pre-level state); subsequent dispatches run one
                    # level at a time with a cold-tier probe each
                    with tel.span("tier.spill", keys=seen_count):
                        self._tier_spill_prefix(np.asarray(seen),
                                                seen_count)
                    seen = jnp.asarray(
                        np.full((old, K), SENTINEL, np.int32))
                    seen_count = 0
                    self.log(f"-- tier: device seen cap "
                             f"{self.seen_cap} reached; spilled the "
                             f"device tier to "
                             f"host={self._tiers.host_keys}/"
                             f"disk={self._tiers.disk_keys} keys "
                             f"(level {depth} redone)")
                    state = (seen, jnp.int32(seen_count), frontier,
                             jnp.int32(fcount), jnp.int32(distinct),
                             jnp.int32(summary[4]),
                             jnp.int32(summary[5]), jnp.int32(depth))
                    continue
                # x4: each growth recompiles the whole program, so
                # over-shooting is much cheaper than growing twice
                caps[what] = old * 4
                if what == "VC":
                    caps[what] = min(caps[what], self.A * CH)
                if what == "SC" and self.seen_cap is not None:
                    if old < self.seen_cap:
                        # grow the device tier all the way TO the cap
                        # before spilling (the x4 overshoot must not
                        # spill at a fraction of the configured cap)
                        caps[what] = min(caps[what], self.seen_cap)
                    else:
                        # at/above the cap with nothing left to spill
                        # (the rolled-back table is empty): one
                        # level's new keys alone exceed the cap — grow
                        # past it, named, exactly like the level
                        # engine's soft breach (a clamp here would be
                        # zero growth: an infinite redo of the same
                        # dispatch)
                        self.log(f"-- tier: device cap "
                                 f"{self.seen_cap} < one level's new "
                                 f"keys; growing to {caps[what]} "
                                 f"anyway (soft cap)")
                if what == "SC":
                    pad = jnp.full((caps[what] - old, K), SENTINEL,
                                   jnp.int32)
                    seen = jnp.concatenate([seen, pad])
                elif what == "FCap":
                    pad = jnp.full((caps[what] - old, self.PW), SENTINEL,
                                   jnp.int32)
                    frontier = jnp.concatenate([frontier, pad])
                # keep the cap invariants: AccCap >= 2*VC (block-append
                # headroom) and AccCap >= FCap ([:FCap] frontier slice of
                # the accumulator)
                caps["AccCap"] = max(caps["AccCap"], 2 * caps["VC"],
                                     caps["FCap"])
                self.log(f"-- resident: growing {what} to {caps[what]} "
                         f"(level {depth} redone)")
            elif stat == ST_CONTINUE:
                now = time.time()
                if now - last_progress >= self.progress_every:
                    last_progress = now
                    self.log(f"Progress({depth}): {generated} states "
                             f"generated, {distinct} distinct states "
                             f"found, {fcount} states left on queue."
                             f"{obs.eta_suffix(distinct)}")
                if self.checkpoint_path and \
                        now - last_ck >= self.checkpoint_every:
                    last_ck = now
                    self._write_ck(
                        "resident", caps=dict(caps),
                        seen=np.asarray(seen[:seen_count]),
                        frontier=np.asarray(frontier[:fcount]),
                        distinct=distinct, generated=generated,
                        depth=depth)
            elif stat == ST_DONE:
                # remember enough levels-per-dispatch to cover the whole
                # search in ONE dispatch on a warm re-run (tiny models:
                # per-dispatch overhead dominated the r04 inversion)
                self._res_maxlvl_warm = min(
                    max(depth + 1, maxlvl), self._res_maxlvl)
                self.log("Model checking completed. No error has been "
                         "found.")
                self.log(f"{generated} states generated, {distinct} "
                         f"distinct states found, 0 states left on queue.")
                self.log(f"The depth of the complete state graph search "
                         f"is {depth}.")
                if self._tiers is not None and self._tiers.active:
                    # tier sizes are LEARNED per (module, layout_sig,
                    # platform) like SC/FCap: persist the cold-tier
                    # key total so the next run on this engine knows
                    # the out-of-core magnitude up front
                    self._save_caps_profile(
                        dict(caps, TIERK=_pow2_at_least(
                            max(len(self._tiers), 1), lo=256)),
                        optional=("TIERK",))
                else:
                    self._save_caps_profile(caps)
                if self.checkpoint_path and self.final_checkpoint:
                    # COMPLETED-run checkpoint (serve warm resume): an
                    # empty frontier over the full seen set — resuming
                    # it replays the stored totals in one dispatch
                    self._write_ck(
                        "resident", caps=dict(caps),
                        seen=np.asarray(seen[:seen_count]),
                        frontier=np.zeros((0, self.PW), np.int32),
                        distinct=distinct, generated=generated,
                        depth=depth)
                return self._mk_result(True, distinct, generated,
                                       depth - 1, t0, warnings)
            elif stat == ST_TRUNC:
                self.log("-- state limit reached, search truncated")
                self._save_caps_profile(caps)
                if self.checkpoint_path:
                    # a truncated resident run is RESUMABLE (ISSUE 5):
                    # truncation lands on a level boundary inside the
                    # device loop, so this is exactly the periodic-
                    # checkpoint state — the warm-start bench resumes it
                    # for a steady-state window, and a resumed run's
                    # final counts are bit-identical to an unbounded
                    # cold run (tests/test_warm_bench.py pins it)
                    self._write_ck(
                        "resident", caps=dict(caps),
                        seen=np.asarray(seen[:seen_count]),
                        frontier=np.asarray(frontier[:fcount]),
                        distinct=distinct, generated=generated,
                        depth=depth)
                return self._mk_result(
                    True, distinct, generated, depth, t0, warnings,
                    None, truncated=True,
                    trunc_reason=f"max_states: distinct {distinct} >= "
                                 f"limit {self.max_states}")
            elif stat == ST_OVF_LANES:
                if ovcode == OV_DEMOTED:
                    msg = ("a demoted compile-recovery fired (the "
                           "kernel under-approximates here): run the "
                           "host_seen mode, which demotes the arm to "
                           "the interpreter and restarts — raising "
                           "caps cannot help")
                elif ovcode == OV_PACK:
                    msg = self._pack_ovf_msg()
                else:
                    msg = ("a container exceeded its lane capacity "
                           f"({self._caps_note()})")
                return self._mk_result(
                    False, distinct, generated, depth, t0, warnings,
                    Violation("error", "capacity overflow", [], msg))
            else:
                st = layout.decode_packed(np.asarray(brow))
                note = "state reached by resident-mode search (no trace)"
                if stat == ST_INV:
                    nm = self.inv_fns[which][0] if 0 <= which < \
                        len(self.inv_fns) else "invariant"
                    v = Violation("invariant", nm, [(st, note)])
                elif stat == ST_DEADLOCK:
                    v = Violation("deadlock", "deadlock", [(st, note)])
                else:
                    v = Violation("assert", "Assert", [(st, note)],
                                  "assertion failed in an enabled action")
                return self._mk_result(False, distinct, generated, depth,
                                       t0, warnings, v)
            state = (seen, jnp.int32(seen_count), frontier,
                     jnp.int32(fcount), jnp.int32(distinct),
                     jnp.int32(summary[4]), jnp.int32(summary[5]),
                     jnp.int32(depth))

    def _run_host_seen(self) -> CheckResult:
        from .. import native_store
        t0 = time.time()
        tel = obs.current()
        model = self.model
        layout = self.layout
        warnings = ["seen-set resident in the native host fingerprint "
                    "store (host_seen); dedup on 128-bit fingerprints"]
        warnings.extend(self._temporal_warnings())
        warnings.extend(self._symmetry_warnings())
        warnings.extend(self._por_warnings())
        # device POR (ISSUE 18): the ample check probes the native store
        # BEFORE insert via contains(); the store grows chunk-by-chunk, so
        # this engine's probe is (soundly) MORE conservative than the
        # pre-level snapshot the level/resident engines use — a state
        # found by an earlier chunk of the same level counts as seen here
        por_plan = self._por_plan() if self.por else None
        if self.seen_cap is not None:
            # the native store is already host-resident (its growth IS
            # the host tier): name the dropped option instead of
            # silently ignoring it (ISSUE 12)
            self.log("-- host_seen: --seen-cap/JAXMC_SEEN_CAP is "
                     "ignored here (the native fingerprint store is "
                     "host-resident; tier spill applies to the "
                     "device-table modes)")

        init_rows, explored_init, n_init, err = \
            self._prepare_init(t0, warnings)
        if err is not None:
            return err
        generated = n_init
        distinct = len(explored_init)

        store = native_store.FingerprintStore()
        init_keys, init_packed, init_povf = self._host_keys(init_rows)
        if init_povf:
            return self._mk_result(
                False, distinct, generated, 0, t0, warnings,
                Violation("error", "capacity overflow", [],
                          self._pack_ovf_msg()))
        store.insert(init_keys[:, 1:])  # drop the validity lane

        # the frontier lives host-side as a dense PACKED row matrix; each
        # level is processed in fixed-size chunks so the [A, chunk, W]
        # expand tensor is memory-bounded and the jit compiles ONE shape
        CH = _pow2_at_least(self.chunk, lo=64)
        frontier_np = np.ascontiguousarray(init_packed[explored_init])

        graph = _LiveGraph(self.labels_flat, self.collect_edges) \
            if self.live_obligations else None
        frontier_sids = graph.add_inits(init_packed, explored_init) \
            if graph is not None else None

        trace_levels = [(np.asarray(init_packed), None, 0)]
        frontier_maps = [np.asarray(explored_init, dtype=np.int64)]
        depth = 0
        if self.resume_from:
            ck = self._load_ck("host_seen")
            (distinct, generated, depth, tl, fm, graph,
             fsids) = self._restore_ck_state(ck, graph)
            if self.store_trace:
                trace_levels, frontier_maps = tl, fm
            if graph is not None:
                frontier_sids = fsids
            store.load(ck["store"])
            frontier_np = np.ascontiguousarray(ck["frontier"])
        # first progress line immediately (ISSUE 2), in this engine's own
        # interval-line format (see the loop's progress_every site)
        self.log(f"Progress({depth}): {generated} generated, "
                 f"{distinct} distinct, {len(frontier_np)} on "
                 f"queue.{obs.eta_suffix(distinct)}")
        last_progress = last_ck = time.time()
        # cross-model batching hook (ISSUE 13): a batch member's device
        # call routes through the shared vmapped dispatcher instead of
        # its own jit — same signature, same outputs, one dispatch for
        # the whole cohort
        hstep = self._hstep_override(CH) \
            if self._hstep_override is not None else self._get_hstep(CH)
        while len(frontier_np) > 0:
            # chaos sites: simulated hard crash / terminal device failure
            # entering a level (no-ops unless JAXMC_FAULTS names them)
            from .. import faults
            faults.kill_self("run_kill", level=depth, engine="host_seen")
            faults.inject("device_run_fail", level=depth)
            if self._drain_requested(warnings, "host_seen"):
                if self.checkpoint_path:
                    self._write_ck(
                        "host_seen", store=store.dump(),
                        frontier=frontier_np,
                        **self._ck_state_kwargs(distinct, generated,
                                                depth, trace_levels,
                                                frontier_maps, graph,
                                                frontier_sids))
                    self._write_host_snapshot(trace_levels, frontier_maps,
                                              graph, depth, generated)
                return self._mk_result(True, distinct, generated, depth,
                                       t0, warnings, None,
                                       truncated=True, drained=True)
            L = len(frontier_np)
            lvl_t0 = time.time()
            lvl_gen0 = generated
            lvl_new_rows: List[np.ndarray] = []
            lvl_new_prov: List[np.ndarray] = []
            lvl_explore: List[np.ndarray] = []
            lvl_edges: List[Tuple[np.ndarray, np.ndarray]] = []
            lvl_dead = np.zeros(L, bool)  # deferred when fb arms exist
            inv_hit = None

            # SURVEY §2.3 pipeline overlap: chunk i+1 is DISPATCHED to
            # the device before chunk i's outputs are forced, so
            # successor generation overlaps the host-side spill (native
            # store insert), deferred predicate checks, and trace
            # bookkeeping. Exact: the device step depends only on its
            # own chunk, and host processing stays in chunk order.
            # Only when the step actually dispatches asynchronously
            # (the fused jit path — _get_hstep tags it): prefetching a
            # synchronous split step yields no overlap and pays one
            # full wasted chunk on every early exit (OV_DEMOTED
            # restarts included). Cost when active: TWO chunks'
            # [A*CH, W] outputs live at once — size --chunk with that
            # 2x in mind, or set JAXMC_NO_PREFETCH=1 to restore the
            # sequential loop when the doubled working set won't fit
            prefetch = getattr(hstep, "is_async", False) and \
                os.environ.get("JAXMC_NO_PREFETCH") != "1"

            def _dispatch(b, fnp=frontier_np, ll=L):
                c = min(CH, ll - b)
                bf = np.full((CH, self.PW), SENTINEL, np.int32)
                bf[:c] = fnp[b:b + c]
                return b, c, bf, hstep(bf, c)

            nxt = None  # one-slot prefetch: the chunk dispatched early
            for base in range(0, L, CH):
                _b, cn, buf, out = nxt if nxt is not None \
                    else _dispatch(base)
                nxt = _dispatch(base + CH) \
                    if prefetch and base + CH < L else None
                ovc = int(out["overflow"])
                if ovc:
                    self._last_ovf_code = ovc
                    self._last_frontier_np = frontier_np
                    if ovc == OV_DEMOTED:
                        msg = ("a demoted compile-recovery fired (the "
                               "kernel under-approximates here); the "
                               "hybrid engine demotes the arm and "
                               "restarts")
                    elif ovc == OV_PACK:
                        msg = self._pack_ovf_msg()
                    else:
                        msg = ("a container exceeded its lane capacity "
                               f"({self._caps_note()})")
                    return self._mk_result(
                        False, distinct, generated, depth, t0, warnings,
                        Violation("error", "capacity overflow", [], msg))
                if _any_fast(out["assert_bad"]):
                    ab = np.asarray(out["assert_bad"])
                    ai, f = np.unravel_index(np.argmax(ab), ab.shape)
                    trace = self._trace_to(trace_levels, frontier_maps,
                                           depth, base + int(f))
                    return self._mk_result(
                        False, distinct, generated, depth, t0, warnings,
                        Violation("assert", "Assert",
                                  [x for x in trace if x[0] is not None],
                                  f"assertion in "
                                  f"{self.labels_flat[int(ai)]}"))
                if model.check_deadlock and _any_fast(out["dead"]):
                    if self.fb_arms:
                        # a device-dead state may still have fallback-arm
                        # successors: defer the verdict to after the
                        # interpreter expansion of this level
                        lvl_dead[base:base + cn] = \
                            np.asarray(out["dead"])[:cn]
                    else:
                        f = int(np.argmax(np.asarray(out["dead"])))
                        trace = self._trace_to(trace_levels,
                                               frontier_maps,
                                               depth, base + f)
                        return self._mk_result(
                            False, distinct, generated, depth, t0,
                            warnings,
                            Violation("deadlock", "deadlock", trace))

                cvalid = np.asarray(out["cvalid"])
                keys = np.asarray(out["keys"])
                if por_plan is not None:
                    vidx = np.nonzero(cvalid)[0]
                    found = np.zeros(len(cvalid), dtype=bool)
                    if len(vidx):
                        found[vidx] = store.contains(keys[vidx][:, 1:])
                    keep, n_amp, n_exp = _por_mask_np(
                        found, cvalid, por_plan["inst_arm"],
                        por_plan["arm_safe"], self.A, CH)
                    self._por_stats["ample"] += int(n_amp)
                    self._por_stats["expanded"] += int(n_exp)
                    self._por_stats["masked"] += \
                        int(np.sum(cvalid & ~keep))
                    cvalid = keep
                    generated += int(np.sum(keep))
                else:
                    generated += int(out["gen"])
                deferred = out.get("deferred_preds", False)
                explore = np.asarray(out["explore"]) \
                    if "explore" in out else None
                if self.refiners:
                    # need_edges implies explore is present in both modes
                    rviol = self._refine_edges(buf, out["cand"], cvalid,
                                               explore, CH)
                    if rviol is not None:
                        a, f, sst, rc = rviol
                        trace = self._trace_to(trace_levels,
                                               frontier_maps,
                                               depth, base + f)
                        return self._mk_result(
                            False, distinct, generated, depth, t0,
                            warnings,
                            self._refine_violation(rc, sst, a, trace))
                if graph is not None and graph.collect_edges:
                    # keep only the masked kept-candidate rows (the full
                    # [A*CH, W] tensor per chunk would hold the whole
                    # level expansion in host RAM)
                    eidx = np.nonzero(cvalid & explore)[0]
                    erows = np.asarray(jnp.take(
                        out["cand"], jnp.asarray(eidx, dtype=jnp.int32),
                        axis=0)) if len(eidx) \
                        else np.zeros((0, self.PW), np.int32)
                    lvl_edges.append((erows, base + eidx % CH))
                valid_idx = np.nonzero(cvalid)[0]
                new_mask = store.insert(keys[valid_idx][:, 1:])
                new_idx = valid_idx[new_mask]
                if not len(new_idx):
                    continue
                rows_np = _take_rows_fast(out["cand"], new_idx)
                # predicate checks run on NEW rows only (TLC checks each
                # state once): the split hstep defers them entirely —
                # evaluating MCVoting's quantifier-heavy Inv over every
                # one of the A*CH padded candidates was the r3 sweep's
                # compile timeout
                if deferred:
                    inv_okn, exploren = self._check_new_rows(
                        rows_np, skip_cons=explore is not None)
                    if explore is not None:  # need_edges: cons per cand
                        exploren = explore[new_idx]
                else:
                    inv_okn = np.asarray(out["inv_ok"])[new_idx]
                    exploren = explore[new_idx]
                if self.fb_cons:
                    # hybrid: uncompilable CONSTRAINTs evaluate on the
                    # host over decoded new rows (same discard semantics)
                    for k in range(len(rows_np)):
                        if not exploren[k]:
                            continue
                        cctx = model.ctx(
                            state=layout.decode_packed(rows_np[k]))
                        for cnm, cex, _r in self.fb_cons:
                            if not _bool(eval_expr(cex, cctx),
                                         f"constraint {cnm}"):
                                exploren[k] = False
                                break
                # discarded (constraint-violating) states are in the store
                # (fingerprinted) but never counted distinct, checked, or
                # explored — TLC semantics (testout2:265)
                distinct += int(exploren.sum())
                # global provenance: action a, parent base+f within the
                # level's full frontier of length L (cand index = a*CH + f)
                a_ids = new_idx // CH
                f_ids = new_idx % CH
                prov_global = a_ids * L + (base + f_ids)
                bad_mask = (~inv_okn) & exploren
                if inv_hit is None and bad_mask.any():
                    off = sum(len(r) for r in lvl_new_rows)
                    badpos = int(np.nonzero(bad_mask)[0][0])
                    inv_hit = off + badpos
                lvl_new_rows.append(rows_np)
                lvl_new_prov.append(prov_global.astype(np.int64))
                lvl_explore.append(exploren)
                if inv_hit is not None:
                    # the violation is already in hand: skip the rest of
                    # the level's chunks
                    break

            if self.fb_arms and inv_hit is None:
                # hybrid: interpreter-enumerate the fallback arms over
                # this level's frontier and splice the results into the
                # same level streams (rows/prov/explore/edges)
                fb_enabled = np.zeros(L, bool)
                gen_inc, dist_inc, fbv = self._fb_expand_level(
                    frontier_np, L, store, lvl_new_rows, lvl_new_prov,
                    lvl_explore, lvl_edges, fb_enabled,
                    trace_levels, frontier_maps, depth, t0, warnings,
                    distinct, generated)
                if fbv is not None:
                    return fbv
                generated += gen_inc
                distinct += dist_inc
                if model.check_deadlock:
                    dead_final = lvl_dead & ~fb_enabled
                    if dead_final.any():
                        f = int(np.nonzero(dead_final)[0][0])
                        trace = self._trace_to(trace_levels,
                                               frontier_maps, depth, f)
                        return self._mk_result(
                            False, distinct, generated, depth, t0,
                            warnings,
                            Violation("deadlock", "deadlock", trace))

            new_rows_np = np.concatenate(lvl_new_rows) if lvl_new_rows \
                else np.zeros((0, self.PW), np.int32)
            new_prov_np = np.concatenate(lvl_new_prov) if lvl_new_prov \
                else np.zeros(0, np.int64)
            explore_mask = np.concatenate(lvl_explore) if lvl_explore \
                else np.zeros(0, bool)

            if inv_hit is None and self.fb_invs:
                # hybrid: uncompilable INVARIANTs evaluate on the host
                # over this level's kept (explored) new states
                for pos in np.nonzero(explore_mask)[0]:
                    ictx = model.ctx(state=layout.decode_packed(
                        new_rows_np[pos]))
                    bad = False
                    for inm, iex, _r in self.fb_invs:
                        if not _bool(eval_expr(iex, ictx),
                                     f"invariant {inm}"):
                            bad = True
                            break
                    if bad:
                        inv_hit = int(pos)
                        break

            if self.store_trace:
                trace_levels.append((new_rows_np, new_prov_np, L))
            if inv_hit is not None:
                st = layout.decode_packed(new_rows_np[inv_hit])
                ctx = model.ctx(state=st)
                nm = next((n for n, ex in model.invariants
                           if not _bool(eval_expr(ex, ctx), n)),
                          model.invariants[0][0] if model.invariants
                          else "invariant")
                trace = self._trace_to(trace_levels, frontier_maps,
                                       depth + 1, inv_hit,
                                       from_new=True) \
                    if self.store_trace else [(st, "?")]
                return self._mk_result(
                    False, distinct, generated, depth + 1, t0, warnings,
                    Violation("invariant", nm, trace))

            sel = np.nonzero(explore_mask)[0]
            if graph is not None:
                new_sids = graph.add_level(new_rows_np[sel],
                                           new_prov_np[sel], L,
                                           frontier_sids)
                for erows, eparents in lvl_edges:
                    graph.add_edges(erows, eparents, frontier_sids)
                frontier_sids = new_sids
            if self.store_trace:
                frontier_maps.append(sel.astype(np.int64))
            tel.level(depth, frontier=L, generated=generated - lvl_gen0,
                      new=len(sel), distinct=distinct, seen=len(store),
                      wall_s=round(time.time() - lvl_t0, 6))
            self._fp_occupancy = len(store)
            depth += 1
            if self.max_states and distinct >= self.max_states:
                self.log("-- state limit reached, search truncated")
                return self._mk_result(
                    True, distinct, generated, depth, t0, warnings,
                    None, truncated=True,
                    trunc_reason=f"max_states: distinct {distinct} >= "
                                 f"limit {self.max_states}")
            frontier_np = new_rows_np[sel]

            now = time.time()
            if self.checkpoint_path and \
                    now - last_ck >= self.checkpoint_every:
                last_ck = now
                self._write_ck(
                    "host_seen", store=store.dump(), frontier=frontier_np,
                    **self._ck_state_kwargs(distinct, generated, depth,
                                            trace_levels, frontier_maps,
                                            graph, frontier_sids))
                self._write_host_snapshot(trace_levels, frontier_maps,
                                          graph, depth, generated)
            if now - last_progress >= self.progress_every:
                last_progress = now
                self.log(f"Progress({depth}): {generated} generated, "
                         f"{distinct} distinct, {len(frontier_np)} on "
                         f"queue.{obs.eta_suffix(distinct)}")

        if graph is not None:
            viol = self._check_live(graph, warnings)
            if viol is not None:
                return self._mk_result(False, distinct, generated,
                                       depth - 1, t0, warnings, viol)
        self.log("Model checking completed. No error has been found.")
        self.log(f"{generated} states generated, {distinct} distinct "
                 f"states found, 0 states left on queue.")
        if self.checkpoint_path and self.final_checkpoint:
            # COMPLETED-run checkpoint (serve warm resume): an empty
            # frontier over the full store — resuming it skips the
            # level loop and replays the stored totals
            self._write_ck(
                "host_seen", store=store.dump(),
                frontier=np.zeros((0, self.PW), np.int32),
                **self._ck_state_kwargs(distinct, generated, depth,
                                        trace_levels, frontier_maps,
                                        graph, frontier_sids))
        return self._mk_result(True, distinct, generated, depth - 1, t0,
                               warnings)

    def _fb_expand_level(self, frontier_np, L, store, lvl_new_rows,
                         lvl_new_prov, lvl_explore, lvl_edges, fb_enabled,
                         trace_levels, frontier_maps, depth, t0, warnings,
                         distinct, generated):
        """Hybrid execution, action side (VERDICT r3 #2): enumerate the
        fallback arms with the EXACT interpreter over this level's
        decoded frontier states, encode the successors, dedup them
        through the native store, and splice rows/provenance into the
        level streams so traces, refinement, and the liveness behavior
        graph see one uniform level. Fallback arm j uses provenance
        action index A + j (labels_flat is extended accordingly).

        Returns (generated_inc, distinct_inc, violation CheckResult |
        None); mutates lvl_* and fb_enabled in place."""
        model = self.model
        layout = self.layout
        base_ctx = model.ctx()
        gen_inc = 0
        cand_rows: List[np.ndarray] = []
        cand_prov: List[int] = []

        def _mk(viol):
            return self._mk_result(False, distinct, generated + gen_inc,
                                   depth, t0, warnings, viol)

        decoded = [layout.decode_packed(frontier_np[f]) for f in range(L)]
        for j, (arm, _reason) in enumerate(self.fb_arms):
            ctx = base_ctx.with_bound(arm.bound)
            for f in range(L):
                pst = decoded[f]
                try:
                    succs = [s for s, _ in enumerate_next(
                        arm.expr, ctx, model.vars, pst)]
                except TLCAssertFailure as ex:
                    trace = self._trace_to(trace_levels, frontier_maps,
                                           depth, f)
                    return gen_inc, 0, _mk(Violation(
                        "assert", "Assert",
                        [x for x in trace if x[0] is not None],
                        str(ex.out)))
                if succs:
                    fb_enabled[f] = True
                gen_inc += len(succs)
                for sst in succs:
                    # constraint check FIRST: a discarded successor is
                    # never explored, counted, or edge-checked, so it
                    # needs no encoding at all — its value shapes may
                    # legitimately be absent from the sampled layout
                    # (skew_fast's cfg discards abort histories, so no
                    # sampled state holds an abort record). Dropping it
                    # here is count-equivalent to fingerprint-and-
                    # discard: satisfaction is state-determined, so the
                    # state can never reappear in an explored context.
                    if not satisfies_constraints(model, sst):
                        continue
                    try:
                        row = np.asarray(layout.encode(sst), np.int32)
                    except (CompileError, EvalError) as ex:
                        # ANY fallback-encode failure is an OBSERVATION
                        # gap relayout can fix: missing variants get
                        # their union slot, and capacity shortfalls grow
                        # because build_layout2 re-derives caps from the
                        # enriched observations. The failing state rides
                        # along so recovery is deterministic even when
                        # the frontier outgrows the enrichment cap.
                        self._last_ovf_code = OV_DEMOTED
                        self._relayout_hint = True
                        self._last_frontier_np = frontier_np
                        self._relayout_states = [sst]
                        return gen_inc, 0, _mk(Violation(
                            "error", "capacity overflow", [],
                            "a fallback successor exceeded its lane "
                            f"capacity ({ex}; {self._caps_note()}); "
                            "counts would no longer be exact"))
                    # EVERY invariant (compiled and demoted alike)
                    # checks host-side on fallback successors: the
                    # device inv pass only sees device candidates
                    ictx = model.ctx(state=sst)
                    for inm, iex in model.invariants:
                        if not _bool(eval_expr(iex, ictx),
                                     f"invariant {inm}"):
                            trace = self._trace_to(
                                trace_levels, frontier_maps, depth, f)
                            trace = [x for x in trace
                                     if x[0] is not None]
                            trace.append(
                                (sst, self.labels_flat[self.A + j]))
                            return gen_inc, 0, _mk(Violation(
                                "invariant", inm, trace))
                    if self.refiners:
                        for rc in self.refiners:
                            if not rc.check_edge(pst, sst):
                                trace = self._trace_to(
                                    trace_levels, frontier_maps, depth, f)
                                return gen_inc, 0, _mk(
                                    self._refine_violation(
                                        rc, sst, self.A + j, trace))
                    cand_rows.append(row)
                    cand_prov.append((self.A + j) * L + f)

        if not cand_rows:
            return gen_inc, 0, None
        # every row collected above is constraint-satisfying (discarded
        # successors were dropped before encoding — they are never
        # counted, checked, or explored, so the drop is count-equivalent
        # to TLC's fingerprint-and-discard)
        rows_mat = np.stack(cand_rows)
        keys, packed_mat, povf = self._host_keys(rows_mat)
        if povf:
            # a packed-lane overflow on a fallback successor is the same
            # OBSERVATION-GAP class as an encode failure: relayout
            # re-profiles the lane ranges from the enriched samples
            self._last_ovf_code = OV_DEMOTED
            self._relayout_hint = True
            self._last_frontier_np = frontier_np
            self._relayout_states = []
            return gen_inc, 0, _mk(Violation(
                "error", "capacity overflow", [],
                f"a fallback successor escaped its packed lane range "
                f"({self._pack_ovf_msg()})"))
        if self.collect_edges:
            # every explored successor EDGE (revisits included) feeds the
            # behavior graph, mirroring the device candidate stream
            lvl_edges.append(
                (packed_mat, np.asarray([p % L for p in cand_prov])))
        new_mask = store.insert(keys[:, 1:])
        new_idx = np.nonzero(new_mask)[0]
        dist_inc = len(new_idx)
        if len(new_idx):
            lvl_new_rows.append(packed_mat[new_idx])
            lvl_new_prov.append(np.asarray(
                [cand_prov[i] for i in new_idx], np.int64))
            lvl_explore.append(np.ones(len(new_idx), bool))
        return gen_inc, dist_inc, None

    def _relayout_and_restart(self) -> Optional[CheckResult]:
        """Adaptive relayout (hybrid): decode the abort-time frontier,
        interp-enumerate one exact level of its successors, and build a
        FRESH engine whose layout sampling includes those states — the
        value shape that fired the demotion is then observed, its union
        variant exists, and the restarted search stays compiled.
        Returns the fresh engine's result, or None when enrichment
        fails (caller falls back to arm demotion)."""
        model = self.model
        cap = 20000
        rows = self._last_frontier_np
        if len(rows) > cap:
            if self.relayouts_left <= 1 and len(rows) <= 10 * cap:
                # last attempt: pay for the FULL frontier (bounded at
                # 10x the per-attempt cap) — a sample that misses the
                # offending parent row would repeat the same abort and
                # waste the attempt. Frontiers beyond the bound stay
                # strided; arm demotion remains the exact safety valve
                self.log(f"hybrid: final relayout attempt — enriching "
                         f"from ALL {len(rows)} abort-frontier rows")
            else:
                # stride over the WHOLE frontier (not a prefix: the
                # missing variant's parent can sit anywhere), with a
                # per-attempt offset so a repeated abort at the same
                # frontier enriches from DIFFERENT rows each time
                stride = -(-len(rows) // cap)
                off = self.relayouts_left % stride
                self.log(f"hybrid: relayout enrichment strided (rows "
                         f"{off}::{stride} of {len(rows)} in the abort "
                         f"frontier)")
                rows = rows[off::stride]
        # states whose encode failed are known exactly — include them
        # directly so recovery never depends on the cap
        enrich: List[Dict[str, Any]] = list(self._relayout_states)
        base_ctx = model.ctx()
        enrich_cap = 400_000  # hard memory ceiling on successor dicts
        try:
            for row in rows:
                # frontier states themselves are already encodable (they
                # were just decoded from this layout): only their
                # SUCCESSORS can carry unobserved shapes
                st = self.layout.decode_packed(np.asarray(row))
                for succ, _ in enumerate_next(model.next, base_ctx,
                                              model.vars, st):
                    enrich.append(succ)
                if len(enrich) >= enrich_cap:
                    self.log(f"hybrid: relayout enrichment truncated "
                             f"at {len(enrich)} successor states "
                             f"(memory ceiling)")
                    break
        except (EvalError, TLCAssertFailure):
            return None
        self.log(f"hybrid: adaptive relayout — re-sampling with "
                 f"{len(enrich)} abort-frontier states, rebuilding "
                 f"kernels, restarting compiled "
                 f"({self.relayouts_left - 1} attempts left)")
        obs.current().counter("expand.relayouts")
        obs.current().reset_levels("adaptive relayout restart")
        if self.checkpoint_path:
            # a checkpoint written under the enriched layout could not
            # be resumed (the resume path re-derives the layout from
            # plain sampling, so the layout signature would mismatch):
            # disable checkpointing rather than strand the user with an
            # unresumable file. Persisting enrichment states in the
            # checkpoint is the known follow-up (ROADMAP).
            self.log("hybrid: relayout disables checkpointing for the "
                     "restarted run (the enriched layout would make "
                     "checkpoints unresumable)")
        try:
            ex2 = TpuExplorer(
                model, log=self.log, max_states=self.max_states,
                store_trace=self.store_trace,
                progress_every=self.progress_every, bounds=self.bounds,
                sample_cfg=self.sample_cfg, host_seen=True,
                chunk=self.chunk,
                extra_samples=self.extra_samples + enrich,
                relayouts_left=self.relayouts_left - 1)
        except (CompileError, ModeError):
            return None
        return ex2.run()

    def _demote_arms(self, arm_idxs) -> List[str]:
        """Hybrid runtime demotion: move the given arms' compiled
        kernels to the interpreter-fallback list and clear the step
        caches. Called when a demoted guard conjunct's abort flag fires
        (see __init__._demotable); the caller restarts the search."""
        idxset = set(arm_idxs)
        reasons: Dict[int, List[str]] = {ai: [] for ai in idxset}
        labels: List[str] = []
        for i, ca in enumerate(self.compiled):
            ai = self._ca_arm[i]
            if ai in idxset:
                reasons[ai].extend(ca.demoted_guards)
                labels.append(ca.label)
        keep = [(ga, ca, ai) for ga, ca, ai in
                zip(self.actions, self.compiled, self._ca_arm)
                if ai not in idxset]
        self.actions = [g for g, _, _ in keep]
        self.compiled = [c for _, c, _ in keep]
        self._ca_arm = [a for _, _, a in keep]
        self.labels_flat = []
        for ca in self.compiled:
            if ca.n_slots:
                self.labels_flat.extend([ca.label] * ca.n_slots)
            else:
                self.labels_flat.append(ca.label)
        self.A = len(self.labels_flat)
        for ai in sorted(idxset):
            why = "; ".join(dict.fromkeys(reasons[ai])) or \
                "demoted guard conjunct"
            self.fb_arms.append((self.arms[ai], f"guard demoted: {why}"))
        self.labels_flat = self.labels_flat + \
            [arm.label or "Next" for arm, _ in self.fb_arms]
        self.hybrid = True
        self._demotable = []
        # the engine is hybrid now: a cached POR plan would mask arms
        # the interpreter expands out of the device's sight — recompute
        # (the hybrid refusal fires on the restarted run)
        self._por_memo = _POR_UNSET
        self._step_cache.clear()
        self._hstep_cache.clear()
        # grouped-dispatch plans index the OLD compiled list: stale
        # (jits, inst_blocks) would scatter past the shrunken A
        self._hstep_group_jits.clear()
        self._res_cache.clear()
        obs.current().counter("expand.recovery_demotions", len(idxset))
        return labels

    # ---- host-side search loop ----
    def run(self) -> CheckResult:
        if self.resident:
            return self._run_resident()
        if self.host_seen:
            self._last_ovf_code = 0
            self._relayout_hint = False
            self._relayout_states: List[Dict[str, Any]] = []
            r = self._run_host_seen()
            while not r.ok and r.violation is not None \
                    and r.violation.kind == "error" \
                    and self._last_ovf_code in (OV_DEMOTED, OV_PACK):
                # a compile-recovery demotion fired (never a true lane
                # overflow — that keeps code OV_CAPACITY). First choice:
                # ADAPTIVE RELAYOUT — when the cause is an OBSERVATION
                # gap (a value shape the sampler missed), re-sampling
                # from the abort frontier and rebuilding the kernels
                # keeps the model fully COMPILED. Structural compiler
                # limitations (extensional-set equality, unbounded
                # CHOOSE, Lambda, unsupported binders) can never be
                # fixed by observation — those demote the arms to the
                # interpreter (exact, slower).
                # OV_PACK (a value escaped its packed lane's profiled
                # range) is ALWAYS an observation gap: the relayout's
                # enriched samples re-profile the lane ranges.
                def _structural(why):
                    return ("extensional" in why or
                            "unbounded CHOOSE" in why or
                            "Lambda" in why or "not supported" in why)
                fixable = (self._last_ovf_code == OV_PACK or
                           self._relayout_hint or any(
                               not _structural(why)
                               for ca in self.compiled
                               for why in ca.demoted_guards))
                if fixable and self.relayouts_left > 0 and \
                        self._last_frontier_np is not None and \
                        len(self._last_frontier_np):
                    r2 = self._relayout_and_restart()
                    if r2 is not None:
                        return r2
                if not self._demotable:
                    break
                demoted = self._demote_arms(self._demotable)
                obs.current().reset_levels("hybrid demotion restart")
                self.log(f"hybrid: demotion abort — falling "
                         f"{demoted} back to the interpreter and "
                         f"restarting")
                self._last_ovf_code = 0
                self._relayout_hint = False
                self._relayout_states = []
                r = self._run_host_seen()
            return r
        t0 = time.time()
        tel = obs.current()
        model = self.model
        W, K = self.W, self.K
        warnings = []
        warnings.extend(self._temporal_warnings())
        warnings.extend(self._symmetry_warnings())
        warnings.extend(self._por_warnings())
        if self.fp_mode:
            warnings.append(
                "wide state (W={}): dedup on 128-bit fingerprints; "
                "collision probability < n^2 * 2^-129".format(W))

        init_rows, explored_init, n_init, err = \
            self._prepare_init(t0, warnings)
        if err is not None:
            return err
        generated = n_init
        distinct = len(explored_init)

        init_keys, init_packed, init_povf = self._host_keys(init_rows)
        if init_povf:
            return self._mk_result(
                False, distinct, generated, 0, t0, warnings,
                Violation("error", "capacity overflow", [],
                          self._pack_ovf_msg()))
        graph = _LiveGraph(self.labels_flat, self.collect_edges) \
            if self.live_obligations else None
        frontier_sids = graph.add_inits(init_packed, explored_init) \
            if graph is not None else None

        FC = _pow2_at_least(max(n_init, 1))
        SC = _pow2_at_least(4 * max(n_init, 1))

        front_init = init_packed[explored_init] if n_init else init_packed
        n_front = len(front_init)
        frontier = np.full((FC, self.PW), SENTINEL, np.int32)
        frontier[:n_front] = front_init
        frontier = jnp.asarray(frontier)
        fcount = n_front

        seen = np.full((SC, K), SENTINEL, np.int32)
        if n_init:
            order = np.lexsort(tuple(init_keys[:, i]
                                     for i in reversed(range(K))))
            seen[:n_init] = init_keys[order]
        seen = jnp.asarray(seen)
        seen_count = n_init

        trace_levels: List[Tuple[np.ndarray, Optional[np.ndarray], int]] = []
        trace_levels.append((np.asarray(init_packed), None, 0))
        frontier_maps: List[np.ndarray] = [np.asarray(explored_init,
                                                      dtype=np.int64)]

        depth = 0
        if self.resume_from:
            ck = self._load_ck("level")
            (distinct, generated, depth, tl, fm, graph,
             fsids) = self._restore_ck_state(ck, graph)
            if self.store_trace:
                trace_levels, frontier_maps = tl, fm
            if graph is not None:
                frontier_sids = fsids
            cs, fr = ck["seen"], ck["frontier"]
            SC = _pow2_at_least(len(cs), SC)
            seen_np = np.full((SC, K), SENTINEL, np.int32)
            seen_np[:len(cs)] = cs
            seen = jnp.asarray(seen_np)
            seen_count = len(cs)
            FC = _pow2_at_least(max(len(fr), 1), FC)
            fr_np = np.full((FC, self.PW), SENTINEL, np.int32)
            fr_np[:len(fr)] = fr
            frontier = jnp.asarray(fr_np)
            fcount = len(fr)

        self.log(f"Progress({depth}): {generated} states generated, "
                 f"{distinct} distinct states found, "
                 f"{fcount} states left on queue."
                 f"{obs.eta_suffix(distinct)}")
        last_progress = last_ck = time.time()
        while fcount > 0:
            # chaos sites (see _run_host_seen): crash / device failure
            # entering a level
            from .. import faults
            faults.kill_self("run_kill", level=depth, engine="level")
            faults.inject("device_run_fail", level=depth)
            if self._drain_requested(warnings, "level"):
                if self.checkpoint_path:
                    self._write_ck(
                        "level", seen=np.asarray(seen[:seen_count]),
                        frontier=np.asarray(frontier[:fcount]),
                        **self._ck_state_kwargs(distinct, generated,
                                                depth, trace_levels,
                                                frontier_maps, graph,
                                                frontier_sids))
                    self._write_host_snapshot(trace_levels, frontier_maps,
                                              graph, depth, generated)
                return self._mk_result(True, distinct, generated, depth,
                                       t0, warnings, None,
                                       truncated=True, drained=True)
            lvl_t0 = time.time()
            C = self.A * FC
            if seen_count + C > SC:
                SC2 = _pow2_at_least(seen_count + C, SC)
                if self.seen_cap is not None and SC2 > self.seen_cap \
                        and seen_count > 0:
                    # device tier full (ISSUE 12): compact the sorted
                    # prefix out to the cold tiers and restart the
                    # device table empty, instead of growing past the
                    # cap — kept rows are cold-probed after each step
                    with tel.span("tier.spill", keys=seen_count):
                        self._tier_spill_prefix(np.asarray(seen),
                                                seen_count)
                    seen = jnp.asarray(
                        np.full((SC, K), SENTINEL, np.int32))
                    seen_count = 0
                    SC2 = _pow2_at_least(C, SC)
                    if SC2 > max(SC, self.seen_cap):
                        # the per-level candidate block alone exceeds
                        # the cap: the rank-merge no-overflow invariant
                        # (seen_count + C <= SC) forces a soft breach
                        self.log(f"-- tier: device cap "
                                 f"{self.seen_cap} < one level's "
                                 f"candidate block ({C}); growing "
                                 f"anyway (soft cap)")
                if SC2 > SC:
                    pad = jnp.full((SC2 - SC, K), SENTINEL, jnp.int32)
                    seen = jnp.concatenate([seen, pad])
                    SC = SC2
            step = self._get_step(SC, FC)
            # HBM model (ISSUE 17): the level loop's two device-resident
            # buffers at their current (possibly re-grown) capacities
            obs.note_buffer("level.seen", SC * K * 4)
            obs.note_buffer("level.frontier", FC * self.PW * 4)
            out = step(seen, seen_count, frontier, fcount)

            ovc = int(out["overflow"])
            if ovc:
                if ovc == OV_DEMOTED:
                    msg = ("a demoted compile-recovery fired (the kernel "
                           "under-approximates here): run the host_seen "
                           "mode, which demotes the arm to the "
                           "interpreter and restarts")
                elif ovc == OV_PACK:
                    msg = self._pack_ovf_msg()
                else:
                    msg = ("a container exceeded its lane capacity "
                           f"({self._caps_note()}); "
                           "counts would no longer be exact")
                return self._mk_result(
                    False, distinct, generated, depth, t0, warnings,
                    Violation("error", "capacity overflow", [], msg))
            if bool(jnp.any(out["assert_bad"])):
                ab = np.asarray(out["assert_bad"])
                a, f = np.unravel_index(np.argmax(ab), ab.shape)
                trace = self._trace_to(trace_levels, frontier_maps,
                                       depth, int(f))
                return self._mk_result(
                    False, distinct, generated, depth, t0, warnings,
                    Violation("assert", "Assert",
                              [x for x in trace if x[0] is not None],
                              f"assertion in {self.labels_flat[int(a)]}"))
            if model.check_deadlock and bool(jnp.any(out["dead"])):
                f = int(jnp.argmax(out["dead"]))
                trace = self._trace_to(trace_levels, frontier_maps,
                                       depth, f)
                return self._mk_result(
                    False, distinct, generated, depth, t0, warnings,
                    Violation("deadlock", "deadlock", trace))

            if self.refiners:
                rviol = self._refine_edges(frontier, out["cand"],
                                           out["cvalid"],
                                           out["explore_all"], FC)
                if rviol is not None:
                    a, f, sst, rc = rviol
                    trace = self._trace_to(trace_levels, frontier_maps,
                                           depth, f)
                    return self._mk_result(
                        False, distinct, generated, depth, t0, warnings,
                        self._refine_violation(rc, sst, a, trace))

            front_count = int(out["front_count"])
            generated += int(out["gen"])
            if "por_ample" in out:
                self._por_stats["ample"] += int(out["por_ample"])
                self._por_stats["expanded"] += int(out["por_expanded"])
                self._por_stats["masked"] += int(out["por_masked"])
            # cold-tier membership filter (ISSUE 12): rows the device
            # rank-merge called new may duplicate keys spilled to the
            # host/disk tiers — drop them (order-preserving) before
            # they are counted, traced, or explored: exactly the rows
            # the uncapped run's device merge would have dropped, so
            # counts and traces stay bit-identical
            tier_keep = None
            fr_host = fp_host = None
            if self._tiers is not None and self._tiers.active \
                    and front_count:
                fkeys = np.asarray(out["front_keys"][:front_count, 1:])
                dup = self._tiers.probe(fkeys)
                if dup.any():
                    tier_keep = ~dup
                    fr_host = np.ascontiguousarray(np.asarray(
                        out["front_rows"][:front_count])[tier_keep])
                    fp_host = np.ascontiguousarray(np.asarray(
                        out["front_prov"][:front_count])[tier_keep])
                self._tiers.publish_gauges(int(out["seen_count"]))
            kept_count = len(fr_host) if fr_host is not None \
                else front_count
            distinct += kept_count  # kept states only (discards excluded)
            seen = out["seen"]
            seen_count = int(out["seen_count"])
            tel.level(depth, frontier=fcount, generated=int(out["gen"]),
                      new=kept_count, distinct=distinct, seen=seen_count,
                      wall_s=round(time.time() - lvl_t0, 6))
            self._fp_occupancy = seen_count

            if graph is not None:
                new_sids = graph.add_level(
                    fr_host if fr_host is not None else
                    np.asarray(out["front_rows"][:front_count]),
                    fp_host if fp_host is not None else
                    np.asarray(out["front_prov"][:front_count]),
                    FC, frontier_sids)
                if graph.collect_edges:
                    # the step emits cand/explore_all iff need_edges —
                    # which collect_edges implies
                    mask = np.asarray(out["cvalid"]) & np.asarray(
                        out["explore_all"])
                    idx = np.nonzero(mask)[0]
                    rows = np.asarray(jnp.take(
                        out["cand"], jnp.asarray(idx, dtype=jnp.int32),
                        axis=0)) if len(idx) \
                        else np.zeros((0, self.PW), np.int32)
                    graph.add_edges(rows, idx % FC, frontier_sids)
                frontier_sids = new_sids

            if self.store_trace:
                # trace levels hold the kept states; every kept state is
                # explored, so the frontier map is the identity
                if fr_host is not None:
                    trace_levels.append((fr_host, fp_host, FC))
                else:
                    fr_h = np.asarray(
                        out["front_rows"][:max(front_count, 1)])
                    fp_h = np.asarray(
                        out["front_prov"][:max(front_count, 1)])
                    trace_levels.append(
                        (fr_h[:front_count], fp_h[:front_count], FC))
                frontier_maps.append(
                    np.arange(kept_count, dtype=np.int64))
            if bool(out["inv_bad_any"]):
                idx = int(out["inv_bad_idx"])
                if tier_keep is not None:
                    # a tier-duplicate row can never violate (its state
                    # was invariant-checked when first admitted), so
                    # the violating row survives the filter: re-index
                    # it into the filtered level
                    idx = int(np.sum(tier_keep[:idx]))
                which = int(out["inv_bad_which"])
                nm = self.inv_fns[which][0]
                trace = self._trace_to(trace_levels, frontier_maps,
                                       depth + 1, idx, from_new=True)
                return self._mk_result(
                    False, distinct, generated, depth + 1, t0, warnings,
                    Violation("invariant", nm, trace))
            depth += 1

            if self.max_states and distinct >= self.max_states:
                self.log("-- state limit reached, search truncated")
                return self._mk_result(
                    True, distinct, generated, depth, t0, warnings,
                    None, truncated=True,
                    trunc_reason=f"max_states: distinct {distinct} >= "
                                 f"limit {self.max_states}")

            if kept_count > FC:
                FC = _pow2_at_least(kept_count, FC)
            if fr_host is not None:
                nf_np = np.full((FC, self.PW), SENTINEL, np.int32)
                nf_np[:kept_count] = fr_host
                frontier = jnp.asarray(nf_np)
            else:
                nf = jnp.full((FC, self.PW), SENTINEL, jnp.int32)
                nf = nf.at[:min(front_count, FC)].set(
                    out["front_rows"][:min(front_count, FC)])
                frontier = nf
            fcount = kept_count

            now = time.time()
            if self.checkpoint_path and \
                    now - last_ck >= self.checkpoint_every:
                last_ck = now
                self._write_ck(
                    "level", seen=np.asarray(seen[:seen_count]),
                    frontier=np.asarray(frontier[:fcount]),
                    **self._ck_state_kwargs(distinct, generated, depth,
                                            trace_levels, frontier_maps,
                                            graph, frontier_sids))
                self._write_host_snapshot(trace_levels, frontier_maps,
                                          graph, depth, generated)
            if now - last_progress >= self.progress_every:
                last_progress = now
                self.log(f"Progress({depth}): {generated} states generated, "
                         f"{distinct} distinct states found, "
                         f"{fcount} states left on queue."
                         f"{obs.eta_suffix(distinct)}")

        if graph is not None:
            viol = self._check_live(graph, warnings)
            if viol is not None:
                return self._mk_result(False, distinct, generated,
                                       depth - 1, t0, warnings, viol)
        self.log("Model checking completed. No error has been found.")
        self.log(f"{generated} states generated, {distinct} distinct states "
                 f"found, 0 states left on queue.")
        self.log(f"The depth of the complete state graph search is "
                 f"{depth}.")
        if self.checkpoint_path and self.final_checkpoint:
            # COMPLETED-run checkpoint (serve warm resume): an empty
            # frontier over the full seen table — resuming it skips the
            # level loop and replays the stored totals
            self._write_ck(
                "level", seen=np.asarray(seen[:seen_count]),
                frontier=np.zeros((0, self.PW), np.int32),
                **self._ck_state_kwargs(distinct, generated, depth,
                                        trace_levels, frontier_maps,
                                        graph, frontier_sids))
        return self._mk_result(True, distinct, generated, depth - 1, t0,
                               warnings)

    def _mk_result(self, ok, distinct, generated, diameter, t0, warnings,
                   violation=None, truncated=False,
                   drained=False,
                   trunc_reason: Optional[str] = None) -> CheckResult:
        tel = obs.current()
        tel.high_water("device.mem_high_water_bytes",
                       obs.device_mem_high_water())
        occ = getattr(self, "_fp_occupancy", None)
        if occ is not None:
            tel.gauge("fingerprint.occupancy", occ)
        if truncated and self.live_obligations:
            warnings.append("temporal properties NOT checked: the "
                            "search was truncated (behavior graph "
                            "incomplete)")
        # ISSUE 12 result surface: the dedup-key mode, the fingerprint
        # collision-probability bound over every ADMITTED key (device
        # occupancy + cold tiers — discarded states hold keys too), the
        # tier-hierarchy summary, and the named exhausted resource on
        # truncations (a bare `truncated` flag cannot tell a deliberate
        # --max-states from a capacity wall)
        tiers_stats = None
        if self._tiers is not None and self._tiers.active:
            tiers_stats = self._tiers.stats()
            self._tiers.publish_gauges(occ or 0)
        # device POR end-of-run counters (ISSUE 18): every engine funnels
        # its result through here, so the gauge surface is uniform
        self._por_finish(self._por_stats["ample"],
                         self._por_stats["expanded"],
                         self._por_stats["masked"], distinct)
        seen_mode = "fingerprint" if self.fp_mode else "exact"
        collision_p = None
        if self.fp_mode:
            n = float((occ or 0) +
                      (len(self._tiers) if self._tiers is not None
                       else 0))
            collision_p = n * n * 2.0 ** -129
            tel.gauge("fingerprint.collision_p", collision_p)
        if truncated and trunc_reason is None:
            trunc_reason = "drain" if drained else "unattributed"
        if trunc_reason:
            tel.gauge("truncation.reason", trunc_reason)
        return CheckResult(ok=ok, distinct=distinct, generated=generated,
                           diameter=max(diameter, 0), violation=violation,
                           wall_s=time.time() - t0, truncated=truncated,
                           warnings=warnings, drained=drained,
                           trunc_reason=trunc_reason,
                           seen_mode=seen_mode, collision_p=collision_p,
                           tiers=tiers_stats)

    def _drain_requested(self, warnings, engine: str) -> bool:
        """Cooperative drain poll at a device-safe boundary (between
        dispatches / at a level barrier).  Appends the named warning and
        emits the trace event; the CALLER writes its own mode-specific
        checkpoint and returns a drained result."""
        from .. import drain as _drain
        if not _drain.requested():
            return False
        why = _drain.reason()
        self.log(f"-- drain requested ({why}): stopping at a safe "
                 f"boundary")
        obs.current().event("drain", reason=why, engine=engine)
        warnings.append(
            f"run drained before completion ({why})"
            + (f"; resume with --resume {self.checkpoint_path}"
               if self.checkpoint_path else "; no checkpoint was "
               "configured — progress was discarded"))
        return True

    def _trace_to(self, trace_levels, frontier_maps, level: int, idx: int,
                  from_new: bool = False) -> List[Tuple[Dict, str]]:
        if not self.store_trace:
            return []
        out = []
        lvl = level
        cur = idx
        if not from_new and lvl < len(frontier_maps):
            cur = int(frontier_maps[lvl][cur])
        while lvl >= 0:
            rows, prov, par_FC = trace_levels[lvl]
            row = rows[cur]
            st = self.layout.decode_packed(row)
            if prov is None:
                out.append((st, "Initial predicate"))
                break
            p = int(prov[cur])
            a, f = p // par_FC, p % par_FC
            out.append((st, self.labels_flat[a]))
            lvl -= 1
            cur = int(frontier_maps[lvl][f]) if lvl < len(frontier_maps) \
                else f
        out.reverse()
        return out
