r"""Preflight backend oracle (ISSUE 11 tentpole).

`--backend auto` must answer "which live platform should this run use?"
in SECONDS and then spend the whole deadline measuring on the winner —
not burn the bench window discovering that the TPU tunnel is dead.  The
oracle probes each candidate platform with a TINY representative
program (a multi-key sort + a scatter + a vectorized binary search —
the merge kernel's shape in miniature) inside a TIMEOUT-GUARDED
subprocess, because the known failure mode of a dead accelerator link
is a HANG at device init, and a hang inside the parent would defeat
the whole point (same battle-tested pattern as compile/cache.py's
health probe).

Verdict policy: every platform whose probe completes inside its budget
is LIVE; among live platforms the highest rank wins (tpu > gpu > cpu —
the tiny probe's dispatch wall cannot rank real workloads across
platforms, transfer overhead dominates it on accelerators, so the
measured walls are telemetry and tiebreak, not the ranking).
JAXMC_ORACLE_PICK=wall flips to fastest-dispatch-wins for diagnosis.

Telemetry (obs satellite):
  gauge backend.oracle_choice   the chosen platform
  gauge backend.oracle_probe    {platform: {live, compile_s,
                                dispatch_s, devices, error?}}
  gauge backend.oracle_wall_s   total preflight wall

CLI: `python -m jaxmc.backend.oracle [--smoke] [--deadline S]` prints
one parseable `ORACLE <platform> ...` line per candidate plus the
verdict; --smoke exits non-zero when the oracle blows its deadline or
finds no live platform (the `make backend-check` gate).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

from . import PLATFORM_RANK

_CANDIDATES = ("tpu", "gpu", "cpu")
_VERDICT_CACHE: Optional[Dict] = None

# the probe program's shape: big enough that a pathologically slow
# backend shows, small enough that cpu-XLA finishes in ~a second
_PROBE_N = 8192

_PROBE_SRC = r"""
import json, sys, time
platform = sys.argv[1]
t_import = time.time()
import jax
jax.config.update("jax_platforms", platform)
import jax.numpy as jnp
from jax import lax
import numpy as np
t_ready = time.time()
try:
    devs = jax.devices()
except Exception as ex:
    print(json.dumps({"ok": False, "error": f"{type(ex).__name__}: {ex}"}))
    sys.exit(0)
N = %(N)d
rng = np.random.RandomState(0)
keys = jnp.asarray(rng.randint(-2**31, 2**31 - 1, (N, 4), dtype=np.int64)
                   .astype(np.int32))
sidx = jnp.arange(N, dtype=jnp.int32)

def probe(keys):
    # the merge kernel in miniature: multi-key sort, rank scatter,
    # fixed-trip binary search — the ops the engines live on
    res = lax.sort(tuple(keys[:, j] for j in range(4)) + (sidx,),
                   num_keys=4, is_stable=True)
    sk = jnp.stack(res[:4], axis=1)
    out = jnp.zeros((N, 4), jnp.int32).at[res[4]].set(sk)
    lo = jnp.zeros(N, jnp.int32)
    hi = jnp.full(N, N, jnp.int32)
    def step(_, lohi):
        lo, hi = lohi
        mid = (lo + hi) // 2
        row = jnp.take(sk[:, 0], jnp.clip(mid, 0, N - 1))
        lt = row < keys[:, 0]
        return jnp.where(lt, mid + 1, lo), jnp.where(lt, hi, mid)
    lo, _ = lax.fori_loop(0, 14, step, (lo, hi))
    return out.sum() + lo.sum()

jp = jax.jit(probe)
t0 = time.time()
jp(keys).block_until_ready()
compile_s = time.time() - t0
t0 = time.time()
jp(keys).block_until_ready()
dispatch_s = time.time() - t0
print(json.dumps({"ok": True, "devices": len(devs),
                  "platform": devs[0].platform,
                  "compile_s": round(compile_s, 4),
                  "dispatch_s": round(dispatch_s, 4),
                  "import_s": round(t_ready - t_import, 4)}))
""" % {"N": _PROBE_N}


def _parse_probe(p: subprocess.Popen, out: str, err: str,
                 platform: str) -> Dict:
    line = (out or "").strip().splitlines()[-1:] or [""]
    try:
        r = json.loads(line[0])
    except ValueError:
        tail = ((err or "") + (out or "")).strip() \
            .splitlines()[-1:] or ["no output"]
        return {"live": False,
                "error": f"probe rc={p.returncode}: {tail[0][:160]}"}
    if not r.get("ok"):
        return {"live": False, "error": r.get("error", "probe failed")}
    if r.get("platform") != platform:
        # jax silently fell back (e.g. gpu requested, cpu delivered):
        # that platform is NOT live, whatever the probe timing says
        return {"live": False,
                "error": f"jax delivered {r.get('platform')!r} instead"}
    return {"live": True, "devices": r.get("devices"),
            "compile_s": r.get("compile_s"),
            "dispatch_s": r.get("dispatch_s")}


def probe_platforms(platforms: List[str],
                    deadline_s: float = 8.0) -> Dict[str, Dict]:
    """Probe every candidate CONCURRENTLY under one shared deadline:
    the dead platforms' wedge timeouts overlap instead of queueing, so
    the preflight wall is the SLOWEST probe, not the sum (a serial
    sweep measurably blew the 10s budget on a loaded box).  Each probe
    is its own subprocess so a wedged plugin init costs the deadline,
    never a hung run."""
    from ..obs import context as trace_context
    env = trace_context.child_env()  # probes join the caller's trace
    # children must see the REAL plugin surface: a parent pinned to
    # cpu via JAX_PLATFORMS would make every accelerator probe lie
    env.pop("JAX_PLATFORMS", None)
    t0 = time.time()
    procs: Dict[str, subprocess.Popen] = {}
    out: Dict[str, Dict] = {}
    for plat in platforms:
        try:
            procs[plat] = subprocess.Popen(
                [sys.executable, "-c", _PROBE_SRC, plat],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env)
        except OSError as ex:
            out[plat] = {"live": False,
                         "error": f"probe could not run: {ex}"}
    for plat, p in procs.items():
        left = max(0.1, deadline_s - (time.time() - t0))
        try:
            so, se = p.communicate(timeout=left)
            out[plat] = _parse_probe(p, so, se, plat)
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate()
            out[plat] = {"live": False,
                         "error": f"probe wedged past "
                                  f"{deadline_s:.1f}s "
                                  f"(dead plugin/tunnel?)"}
    return out


def probe_platform(platform: str, timeout_s: float = 8.0) -> Dict:
    """One candidate's probe result: {live, compile_s?, dispatch_s?,
    devices?, error?} (the single-platform convenience wrapper)."""
    return probe_platforms([platform], deadline_s=timeout_s)[platform]


def preflight(deadline_s: float = 10.0, tel=None,
              candidates: Optional[List[str]] = None,
              use_cache: bool = True) -> Dict:
    """Probe the candidate platforms and pick the best live one.

    Returns {"platform": str | None, "probes": {plat: probe},
    "wall_s": float, "reason": str}.  The verdict is cached per process
    (serve daemons and repeated sessions must not re-pay the probes);
    `use_cache=False` forces a fresh sweep."""
    global _VERDICT_CACHE
    if use_cache and _VERDICT_CACHE is not None:
        return _VERDICT_CACHE
    from .. import obs
    tel = tel if tel is not None else obs.current()
    cands = list(candidates or _CANDIDATES)
    t0 = time.time()
    # probe budget leaves 2s of the deadline for subprocess spawn +
    # result collection: a wedged-platform probe costs its full budget,
    # and measured spawn overhead on a loaded 2-core box reaches ~1.5s
    budget = float(os.environ.get("JAXMC_ORACLE_PROBE_TIMEOUT",
                                  str(max(1.0, deadline_s - 2.0))))
    probes = probe_platforms(cands, deadline_s=budget)
    live = [p for p in cands if probes[p].get("live")]
    pick_by_wall = os.environ.get("JAXMC_ORACLE_PICK") == "wall"
    if not live:
        choice, reason = None, "no live platform (all probes failed)"
    elif pick_by_wall:
        choice = min(live,
                     key=lambda p: probes[p].get("dispatch_s") or 1e9)
        reason = "fastest probe dispatch (JAXMC_ORACLE_PICK=wall)"
    else:
        choice = max(live, key=lambda p: PLATFORM_RANK.get(p, 0))
        reason = f"highest-ranked live platform of {live}"
    wall = round(time.time() - t0, 3)
    verdict = {"platform": choice, "probes": probes, "wall_s": wall,
               "reason": reason}
    tel.gauge("backend.oracle_choice", choice or "none")
    tel.gauge("backend.oracle_probe", probes)
    tel.gauge("backend.oracle_wall_s", wall)
    tel.event("backend.oracle", choice=choice, wall_s=wall,
              reason=reason)
    _VERDICT_CACHE = verdict
    return verdict


def reset_cache_for_tests() -> None:
    global _VERDICT_CACHE
    _VERDICT_CACHE = None


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m jaxmc.backend.oracle",
        description="probe visible platforms, pick the best live one")
    ap.add_argument("--deadline", type=float, default=float(
        os.environ.get("JAXMC_ORACLE_DEADLINE", "10")))
    ap.add_argument("--smoke", action="store_true",
                    help="exit 1 unless a live platform was chosen "
                         "inside the deadline (make backend-check)")
    args = ap.parse_args(argv)
    v = preflight(deadline_s=args.deadline, use_cache=False)
    for plat, pr in v["probes"].items():
        if pr.get("live"):
            print(f"ORACLE {plat} live devices={pr['devices']} "
                  f"compile={pr['compile_s']}s "
                  f"dispatch={pr['dispatch_s']}s")
        else:
            print(f"ORACLE {plat} SKIP: {pr.get('error')}")
    print(f"ORACLE verdict {v['platform'] or 'none'} "
          f"wall={v['wall_s']}s ({v['reason']})")
    if args.smoke:
        if v["platform"] is None:
            print("ORACLE FAIL: no live platform", file=sys.stderr)
            return 1
        if v["wall_s"] > args.deadline:
            print(f"ORACLE FAIL: preflight took {v['wall_s']}s "
                  f"> deadline {args.deadline}s", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
