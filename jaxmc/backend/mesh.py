r"""Multi-chip BFS over a jax.sharding.Mesh (SURVEY.md §2.3, §5).

Frontier data-parallelism + fingerprint-space sharding: each device owns
(a) a shard of the frontier, expanded with the SAME compiled kernels as
the single-chip path (compile/kernel2.py — wide layouts, slotted dynamic
\E, capacity buckets), and (b) a hash range of the seen-set, held as
128-bit fingerprints with an explicit validity lane (never in-band
sentinels — a valid state's lane can legitimately equal SENTINEL).

Two exchange strategies route each level's candidates to their owner
shard (chosen per run; `a2a` is the DEFAULT for D > 1,
JAXMC_MESH_EXCHANGE overrides):

  a2a     hash-routes each candidate straight to its owner via
          all_to_all with per-peer buckets of B = C*gamma/D (traffic
          ~C*gamma per device).  Hash skew past gamma lands overflow
          rows in a small per-peer SPILL bucket drained by a second
          all_to_all pass (mesh.a2a_spill); only when the spill also
          overflows is the level rerun with gamma doubled (ISSUE 8).
  gather  all_gathers every candidate to every device (traffic C*D per
          device, no routing state); each device keeps the rows whose
          fingerprint lands in its range — the structural analogue of
          ring-partitioned attention state (SURVEY.md §5).

MESH-RESIDENT superstep loop (ISSUE 8 tentpole; ISSUE 10 made the hot
path O(new) and multi-level): the seen shards, the packed frontier and
the per-level trace ring all stay ON DEVICE across levels; one jitted
shard_map dispatch runs up to maxlvl levels in a lax.while_loop — each
level expands, exchanges, RANK-MERGES against the sorted seen shards
(only the <=R incoming keys are sorted; two binary searches + scatters
shared with the single-chip resident engine, bfs._rank_merge — sort
work no longer scales with the seen set; JAXMC_MESH_RANKMERGE=0 keeps
the PR-8 full-sort as a bit-identical escape hatch, pinned to one
level per dispatch), appends the trace ring and pushes one replicated
[16]-i32 scalar vector into a device-side ring.  The host drains that
ring once per superstep (mesh.host_syncs counts SUPERSTEPS, < level
count — no row traffic), pre-sizes nothing, and only pulls rows on a
violation (trace assembly), at a checkpoint, or never.  The loop exits
early on violation / deadlock / assert / kernel overflow / truncation
/ empty frontier, so violation localization, SIGTERM drain and
checkpointing keep their exact level-boundary semantics; capacity
overflows (seen / frontier / trace ring / a2a bucket) roll the
offending level back inside the step, so the host can grow the named
capacity and redo it.  JAXMC_MESH_SUPERSTEP pins the level budget per
dispatch (1 = the one-level escape hatch); unset, it adapts to
measured dispatch wall like the single-chip resident controller.
Learned capacities (and the settled levels-per-dispatch, MSL) persist
as a profile keyed by (module, layout_sig, D, exchange)
(compile/cache.py variants), so a second mesh run compiles once and
reports window_recompiles == 0.

Refinement and temporal PROPERTYs still check on the mesh via the
LEGACY host loop (the exchanged-candidate stream feeds the same
host-side stepwise refinement and behavior-graph liveness checkers as
the single-chip device modes; store_trace required, resume with
PROPERTYs rejected) — JAXMC_MESH_RESIDENT=0 forces that loop for
diagnosis.

Parity features (VERDICT r2 #5, preserved by the resident loop):
  * counterexample TRACES with action provenance: each kept new-frontier
    row carries its global candidate index (the src lane of the trace
    ring); a violation replays the shortest path exactly like the
    single-chip level mode (store_trace=True, default);
  * NAMED violations: which invariant failed, plus the violating row;
    deadlock/assert report the offending state row the same way;
  * checkpoint/resume at level boundaries (--checkpoint/--resume), the
    TLC states/ equivalent, with full-run count exactness.

The driver validates this path with N virtual CPU devices via
__graft_entry__.dryrun_multichip (no multi-chip hardware needed) on the
raft workload; `make multichip-check` / `make multichip-bench`
(jaxmc/meshbench.py) run the parity and scaling legs.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import obs
from .. import faults
from ..sem.modules import Model
from ..engine.explore import CheckResult, Violation
from ..compile.vspec import ModeError
from ..compile.kernel2 import OV_DEMOTED, OV_PACK
from .bfs import (SENTINEL, TpuExplorer, _LiveGraph, _pow2_at_least,
                  _por_mask, _rank_merge, _seen_probe)

_BIG = np.int32(2 ** 31 - 1)

# device-side scalar ring capacity: the superstep while_loop writes one
# [_NS] scalar vector per level into a [_SS_RINGCAP, _NS] ring the host
# drains once per dispatch — the cap bounds levels-per-dispatch (a ring
# entry is 64 bytes, so the whole ring stays trivially small)
_SS_RINGCAP = 64

# the mesh capacity-profile shape (compile/cache.py variant
# "mesh-d<D>-<exchange>"): per-shard seen keys, per-shard frontier rows,
# trace-ring levels, the a2a bucket factor gamma stored as
# round(gamma * 16) so the profile stays integer-valued, MSL — the
# levels-per-dispatch the superstep controller settled on (ISSUE 10),
# so a fresh engine skips the 1 -> 2 -> 4 ramp — and VC, the rank
# merge's learned valid-candidate capacity (ISSUE 11), so a warm run
# skips the VC growth redo too.  Profiles saved before PR 10/11 simply
# lack MSL/VC (hints max-merge, absent keys default).
_MESH_PROFILE_KEYS = ("SC", "FC", "TRL", "GAM16", "MSL")
# optional cap: only rank-merge runs learn VC (the fullsort escape
# hatch and JAXMC_MESH_VC=off never do) — absent in their profiles,
# never a reason to drop the whole save/load (compile/cache.py)
_MESH_PROFILE_OPT = ("VC",)

# resident-step scalar vector layout (one replicated [NS] i32 vector is
# ALL the host reads per level)
_S_GEN = 0        # psum generated this level
_S_NEW = 1        # psum kept-new (post-constraint) this level
_S_FRONT = 2      # psum next-frontier occupancy
_S_MAXF = 3       # pmax per-shard next-frontier occupancy (true need)
_S_MAXS = 4       # pmax per-shard seen occupancy (true need)
_S_SUMS = 5       # psum seen occupancy
_S_OVC = 6        # pmax kernel overflow code (OV_*; 0 = none)
_S_DEAD = 7       # any deadlocked row (int)
_S_ASSERT = 8     # any failed Assert (int)
_S_INVMIN = 9     # pmin first-violated invariant index (_BIG = none)
_S_FOVF = 10      # frontier outgrew FC (redo after growth)
_S_SOVF = 11      # a seen shard outgrew SC (redo after growth)
_S_TOVF = 12      # trace ring outgrew TRL (redo after growth)
_S_AOVF = 13      # a2a bucket AND spill overflowed (redo, gamma grows)
_S_SPILL = 14     # psum rows drained through the spill pass
_S_MAXDEST = 15   # pmax per-destination bucket occupancy (a2a)
_S_VOVF = 16      # rank merge's valid-candidate block outgrew VC (redo)
_S_MAXV = 17      # pmax per-shard valid-candidate need (grows VC)
_S_PORA = 18      # psum POR singleton-ample states this level (ISSUE 18)
_S_PORX = 19      # psum POR expanded (any-arm-enabled) states this level
_S_PORM = 20      # psum POR-masked candidate rows this level
_NS = 21

# per-device violation-localization vector (fetched only on violation)
_A_INVW = 0
_A_INVSLOT = 1
_A_DEAD = 2
_A_DEADSLOT = 3
_A_ASSERT = 4
_A_ASRTA = 5
_A_ASRTF = 6
_NA = 7


class MeshExplorer(TpuExplorer):
    """BFS with the frontier and seen-set sharded across a device mesh.

    Shares TpuExplorer's whole compile pipeline (layout sampling, slotted
    kernels, compiled invariants/constraints); only the search loop is
    mesh-sharded. Dedup is always on 128-bit fingerprints (the key layout
    the seen shards store)."""

    def __init__(self, model: Model, mesh: Optional[Mesh] = None,
                 log: Callable[[str], None] = None,
                 max_states: Optional[int] = None,
                 progress_every: float = 30.0, store_trace: bool = True,
                 exchange: Optional[str] = None,
                 mesh_caps: Optional[Dict[str, int]] = None, **kw):
        super().__init__(model, log=log, max_states=max_states,
                         progress_every=progress_every,
                         store_trace=store_trace, **kw)
        if mesh is None:
            mesh = Mesh(np.array(jax.devices()), ("d",))
        self.mesh = mesh
        self.D = mesh.devices.size
        # re-describe the backend with the ACTUAL mesh extent (the
        # base descriptor reports the whole visible device set): the
        # profile namespace and the mesh shape must describe the mesh
        # this engine actually shards over (ISSUE 11)
        from . import describe_backend
        self.backend_desc = describe_backend(
            platform=self.backend_desc.platform, device_count=self.D)
        # seen shards store fingerprint keys: force fp mode on any width
        # — which means --seen exact cannot be honored here (ISSUE 12):
        # refuse it the way bfs refuses resident/host_seen, instead of
        # silently fingerprinting past the requested contract
        if getattr(self, "seen_mode_req", "auto") == "exact":
            from ..compile.vspec import ModeError
            raise ModeError(
                "--seen exact is incompatible with the mesh engine "
                "(seen shards store 128-bit fingerprints) — use the "
                "single-device level mode or --backend interp")
        self.fp_mode = True
        self.K = 4 + 1
        # ICI exchange strategy (SURVEY.md §2.3 "communication
        # scheduling"): a2a is the default whenever the mesh has more
        # than one device — its traffic is ~C*gamma per device instead
        # of gather's C*D, and the spill pass makes hash skew cheap.
        # JAXMC_MESH_EXCHANGE overrides; an explicit constructor arg
        # outranks both (tests pin each strategy).
        self._exchange_src = "explicit"
        if exchange is None:
            env = os.environ.get("JAXMC_MESH_EXCHANGE", "").strip()
            if env:
                exchange, self._exchange_src = env, "JAXMC_MESH_EXCHANGE"
            else:
                exchange = "a2a" if self.D > 1 else "gather"
                self._exchange_src = "default"
        if exchange not in ("gather", "a2a"):
            raise ValueError(f"exchange must be 'gather' or 'a2a', "
                             f"got {exchange!r}")
        self.exchange = exchange
        # shard-local merge strategy (ISSUE 10): "rank" keeps each seen
        # shard's valid prefix SORTED as an invariant and merges only
        # the ≤R incoming keys by rank (the single-chip resident
        # engine's O(new) binary-search scatter, shared via
        # bfs._rank_merge); "fullsort" is the PR-8 full
        # [SC+R, K+1]-key stable sort, kept as the JAXMC_MESH_RANKMERGE=0
        # escape hatch (bit-identical counts/traces, pinned by tests).
        self.merge = "fullsort" \
            if os.environ.get("JAXMC_MESH_RANKMERGE", "").strip() == "0" \
            else "rank"
        # levels per resident dispatch (ISSUE 10 supersteps):
        # JAXMC_MESH_SUPERSTEP=<n> pins it (1 = the one-level-per-
        # dispatch escape hatch); unset/auto adapts to measured
        # dispatch wall like the single-chip resident maxlvl
        # controller.  The fullsort merge cannot run under the
        # superstep while_loop (multi-key sort comparators explode XLA
        # compile time there), so it always runs one level per
        # dispatch.
        ss = os.environ.get("JAXMC_MESH_SUPERSTEP", "").strip().lower()
        self._ss_fixed: Optional[int] = None
        if ss not in ("", "0", "auto"):
            try:
                self._ss_fixed = max(1, min(int(ss), _SS_RINGCAP))
            except ValueError:
                self._ss_fixed = None
        if self.merge == "fullsort":
            self._ss_fixed = 1
        # GROUPED expansion (ISSUE 11: PR 7's fused arm groups ported
        # onto the mesh expand path): on XLA:CPU a single jit holding
        # every kernel instance compiles superlinearly (the host_seen
        # engine has split at JAXMC_FUSED_MAX_INSTANCES since ISSUE 7),
        # and the all-inline mesh step hit exactly that wall on
        # many-instance models.  When it would, the resident level runs
        # as ceil(A/fused_max) shard_map'd GROUP expansion dispatches
        # feeding one merge/tail dispatch — candidate order (groups are
        # contiguous in self.compiled order and concatenate in order)
        # and therefore counts/traces stay bit-identical.  One level
        # per dispatch: the group boundary is a host hop, so supersteps
        # cannot fuse across it.  JAXMC_MESH_GROUPED=1/0 forces it
        # either way (tests pin parity with the fused step).
        self._mesh_fused_max = int(os.environ.get(
            "JAXMC_FUSED_MAX_INSTANCES", "24"))
        genv = os.environ.get("JAXMC_MESH_GROUPED", "").strip()
        if genv in ("0", "1"):
            self._grouped = genv == "1"
        else:
            self._grouped = (self.backend_desc.platform == "cpu"
                             and self.A > self._mesh_fused_max)
        if self._grouped:
            self._ss_fixed = 1
        self._mesh_maxlvl_warm = 1  # learned levels-per-dispatch ramp
        self._ss_shrunk = False     # controller ever had to halve?
        self._supersteps = 0
        self._superstep_levels_max = 0
        self._a2a_gamma = 2.0
        self._mesh_step_cache: Dict[Tuple, Callable] = {}
        # skewed-hash fault site (ISSUE 8 satellite): when armed, EVERY
        # state hashes to shard 0 — on both the host init-shard path and
        # the device routing (one owner formula, so they cannot
        # disagree) — forcing the a2a spill pass (and, once the spill
        # overflows, the gamma-doubling rerun) on models far too small
        # to skew naturally.  Counts/traces must stay exact throughout;
        # tests/test_mesh_resident.py pins it.
        self._skew = faults.fire("mesh_skew", devices=self.D) is not None
        # observed per-shard valid-candidate need (max of the scalar
        # ring's _S_MAXV across committed levels): what the durable
        # profile saves as VC (ISSUE 11) — the lean size the NEXT
        # process warm-starts at — while the in-process capacity stays
        # at whatever this run grew to (shrinking it mid-process would
        # recompile the warm window).  Deliberately NOT reset per run.
        self._vc_seen_need = 0
        # resident-loop accounting (ISSUE 8 obs satellite)
        self._spill_rows = 0
        self._max_bucket = 0
        self._shard_balance: Optional[float] = None
        self._lvl_FC: List[int] = []   # expanding FC per ring level
        # learned mesh capacity profile, keyed (module, layout_sig, D,
        # exchange): a second mesh run starts at the learned caps and
        # gamma, so its one warm-up compile covers the run
        # (window_recompiles == 0).  Max-merged with the caller's
        # manifest hint (corpus.Case.mesh_caps).
        self._mesh_caps_hint: Dict[str, int] = dict(mesh_caps or {})
        if self.cap_profile:
            from ..compile.cache import load_capacity_profile
            prof = load_capacity_profile(
                model.module.name, self._layout_sig(),
                variant=self._profile_variant(),
                keys=_MESH_PROFILE_KEYS, optional=_MESH_PROFILE_OPT)
            if prof:
                for kk, vv in prof.items():
                    self._mesh_caps_hint[kk] = max(
                        int(self._mesh_caps_hint.get(kk, 0)), int(vv))
        if self._mesh_caps_hint.get("GAM16"):
            self._a2a_gamma = max(
                self._a2a_gamma, self._mesh_caps_hint["GAM16"] / 16.0)
        if self._mesh_caps_hint.get("MSL"):
            self._mesh_maxlvl_warm = max(
                self._mesh_maxlvl_warm,
                min(int(self._mesh_caps_hint["MSL"]), _SS_RINGCAP))

    def _profile_variant(self) -> str:
        # namespaced by backend platform (ISSUE 11): a TPU mesh's
        # learned caps must never warm a cpu-XLA virtual-device run
        return self.backend_desc.profile_variant(
            f"mesh-d{self.D}-{self.exchange}")

    # ---- hierarchical seen set (ISSUE 12): per-shard tiering ----

    def _mesh_shard_cap(self) -> Optional[int]:
        """Per-shard device seen cap: the engine cap (--seen-cap /
        JAXMC_SEEN_CAP, TOTAL device key rows) divided across the D
        owner-routed shards."""
        if self.seen_cap is None:
            return None
        return _pow2_at_least(max(self.seen_cap // self.D, 64), lo=64)

    def _mesh_tier_spill(self, seen, seen_count, SC: int):
        """Spill every shard's sorted valid prefix into the cold tiers
        as one immutable run each — owner-routed keys PARTITION the key
        space, so a single combined store answers membership for every
        shard — and restart the shards empty.  Returns the reset
        (seen, seen_count) device pair."""
        tel = obs.current()
        scounts = np.asarray(seen_count)
        total = int(scounts.sum())
        seen_np = np.asarray(seen)
        with tel.span("tier.spill", keys=total, shards=self.D):
            t = self._ensure_tiers()
            for dd in range(self.D):
                cnt = int(scounts[dd])
                if cnt:
                    t.spill(np.ascontiguousarray(
                        seen_np[dd, :cnt, 1:]))
            tel.counter("tier.spilled_keys", total)
        empty = np.full((self.D, SC, self.K), SENTINEL, np.int32)
        empty[:, :, 0] = 1
        return jnp.asarray(empty), jnp.asarray(
            np.zeros(self.D, np.int32))

    def _mesh_tier_filter(self, frontier, fcount, tr_rows, tr_src,
                          depth: int, FC: int):
        """Post-commit cold-tier filter for one mesh level (supersteps
        are pinned to 1 while tiering is active): drop frontier rows
        whose keys live in the host/disk runs — per shard, order-
        preserving — and rewrite the level's trace-ring slot with the
        SAME compaction so parent indices recorded by the next level
        keep resolving.  Returns (frontier, fcount, tr_rows, tr_src,
        n_dup)."""
        fr_np = np.asarray(frontier)          # [D, FC, PW]
        fc_np = np.asarray(fcount).astype(np.int32).copy()
        keeps = []
        n_dup = 0
        for dd in range(self.D):
            c = int(fc_np[dd])
            if c == 0:
                keeps.append(None)
                continue
            keep = self._tier_keep_mask(fr_np[dd, :c])
            keeps.append(keep)
            n_dup += int((~keep).sum())
        if n_dup == 0:
            return frontier, fcount, tr_rows, tr_src, 0
        new_fr = np.full_like(fr_np, SENTINEL)
        new_src = None
        src_slot = None
        if self.store_trace:
            src_slot = np.asarray(tr_src[:, depth - 1])
            new_src = np.full((self.D, FC), -1, np.int32)
            obs.current().counter("mesh.row_syncs")
        for dd in range(self.D):
            c = int(fc_np[dd])
            if c == 0:
                continue
            keep = keeps[dd]
            k = int(keep.sum())
            new_fr[dd, :k] = fr_np[dd, :c][keep]
            if new_src is not None:
                new_src[dd, :k] = src_slot[dd, :c][keep]
            fc_np[dd] = k
        frontier = jnp.asarray(new_fr)
        fcount = jnp.asarray(fc_np)
        if self.store_trace:
            tr_rows = tr_rows.at[:, depth - 1].set(jnp.asarray(new_fr))
            tr_src = tr_src.at[:, depth - 1].set(jnp.asarray(new_src))
        return frontier, fcount, tr_rows, tr_src, n_dup

    # ---- the sharded level step ----
    def _a2a_bucket(self, C: int, FC: int) -> int:
        import math
        # floor: R = D*B must cover the frontier capacity FC, or a
        # sparse no-overflow level could hand the next step a frontier
        # narrower than its compiled shape (review r3)
        return max(1, math.ceil(C * self._a2a_gamma / self.D),
                   math.ceil(FC / self.D))

    def _a2a_spill_bucket(self, B: int) -> int:
        # the spill bucket is deliberately small: it exists to absorb
        # ordinary hash skew (a few rows past B on a hot shard), not to
        # double capacity — B//4 keeps the second all_to_all cheap
        return max(1, B // 4)

    def _owner_from_keys(self, keys: np.ndarray) -> np.ndarray:
        """THE ownership formula (keys lane 1 mod D) — one definition
        for every host path; _owner_jnp is its device-side twin (both
        routes call it, so host and device can never disagree).  The
        mesh_skew fault collapses it to shard 0 on BOTH paths."""
        if self._skew:
            return np.zeros(len(keys), np.int64)
        return (keys[:, 1].astype(np.uint32) % np.uint32(self.D)) \
            .astype(np.int64)

    def _owner_jnp(self, key_lane1):
        """Device-side twin of _owner_from_keys over the keys' lane-1
        column (traced int32 [N]) — the ONLY place the exchange
        closures compute ownership."""
        if self._skew:
            return jnp.zeros(key_lane1.shape[0], jnp.int32)
        return (key_lane1.astype(jnp.uint32)
                % jnp.uint32(self.D)).astype(jnp.int32)

    def _route_fn(self, C: int, FC: int) -> Tuple[Callable, int, int, int]:
        """Build the exchange closure shared by the legacy and resident
        steps: route(ckeys, cand, cvalid, me) ->
        (gkeys [R,K], gcand [R,PW], gsrc [R], spill_local,
        a2a_ovf_local, maxdest_local, evalid [R]).
        `evalid` is the EDGE-STREAM validity — every valid exchanged
        row BEFORE ownership masking (gather replicates the full
        candidate set, so the host's device-0 read must not lose
        foreign-owned rows; a2a buckets are disjoint per device and the
        host concatenates all of them, so per-device validity is
        already complete).  Returns (route, R, B, SB); B/SB are 0 in
        gather mode."""
        D, K, PW = self.D, self.K, self.PW
        a2a = self.exchange == "a2a"
        Pw = K + PW + 1  # a2a payload: [keys | packed row | src-index]
        invalid_key_np = np.concatenate(
            [np.ones(1, np.int32), np.full(K - 1, SENTINEL, np.int32)])
        if not a2a:
            R = D * C

            def route_gather(ckeys, cand, cvalid, me):
                invalid_key = jnp.asarray(invalid_key_np)
                # ICI exchange: gather all candidates + keys, keep my
                # range
                gcand = lax.all_gather(cand, "d", tiled=True)   # [R, PW]
                gkeys = lax.all_gather(ckeys, "d", tiled=True)  # [R, K]
                gsrc = jnp.arange(R, dtype=jnp.int32)
                gvalid = gkeys[:, 0] == 0     # explicit validity lane
                owner = self._owner_jnp(gkeys[:, 1])
                mine = gvalid & (owner == me)
                # foreign/invalid rows: validity lane 1 (sorts last),
                # data lanes sentinel so equal keys cannot straddle the
                # mask
                gkeys = jnp.where(mine[:, None], gkeys, invalid_key)
                zero = jnp.zeros((), jnp.int32)
                return (gkeys, gcand, gsrc, zero, jnp.asarray(False),
                        zero, gvalid)

            return route_gather, R, 0, 0

        B = self._a2a_bucket(C, FC)
        SB = self._a2a_spill_bucket(B)
        R = D * (B + SB)
        # HBM model (ISSUE 17): the two a2a payload staging buffers
        # ([D*B, Pw] + [D*SB, Pw] words, both directions), per device
        obs.note_buffer("mesh.a2a_buckets",
                        2 * D * (B + SB) * (K + PW + 1) * 4 * D)

        def route_a2a(ckeys, cand, cvalid, me):
            invalid_key = jnp.asarray(invalid_key_np)
            # hash-route each candidate straight to its owner:
            # bucket-sort by destination, scatter into [D, B] slots,
            # one all_to_all; rows past B land in the [D, SB] SPILL
            # buckets drained by a second all_to_all (ISSUE 8) —
            # traffic per device: D*(B+SB) = ~C*gamma rows instead of
            # gather's C*D.
            dest = jnp.where(cvalid, self._owner_jnp(ckeys[:, 1]), D)
            sperm = lax.sort(
                (dest, jnp.arange(C, dtype=jnp.int32)),
                num_keys=1, is_stable=True)[1]
            sdest = jnp.take(dest, sperm)
            counts = jnp.zeros((D + 1,), jnp.int32).at[dest].add(1)
            excl = jnp.concatenate(
                [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1]])
            pos = jnp.arange(C, dtype=jnp.int32) - jnp.take(excl, sdest)
            # overflow only when bucket AND spill are exhausted; the
            # max per-destination occupancy rides the scalar vector so
            # the host can grow gamma straight to the observed need
            # (one rerun, not log2 doublings)
            a2a_ovf = jnp.any(counts[:D] > B + SB)
            spill_local = jnp.sum(
                jnp.clip(counts[:D] - B, 0, SB)).astype(jnp.int32)
            maxdest_local = jnp.max(counts[:D]).astype(jnp.int32)
            srcid = me.astype(jnp.int32) * C + sperm
            payload = jnp.concatenate(
                [jnp.take(ckeys, sperm, axis=0),
                 jnp.take(cand, sperm, axis=0),
                 srcid[:, None]], axis=1)              # [C, Pw]
            slot1 = jnp.where((sdest < D) & (pos < B),
                              sdest * B + pos, D * B)
            spos = pos - B
            slot2 = jnp.where((sdest < D) & (spos >= 0) & (spos < SB),
                              sdest * SB + spos, D * SB)
            b1 = jnp.full((D * B + 1, Pw), SENTINEL, jnp.int32)
            b1 = b1.at[:, 0].set(1)  # invalid slots
            b1 = b1.at[slot1].set(payload, mode="drop")
            b2 = jnp.full((D * SB + 1, Pw), SENTINEL, jnp.int32)
            b2 = b2.at[:, 0].set(1)
            b2 = b2.at[slot2].set(payload, mode="drop")
            recv1 = lax.all_to_all(
                b1[:D * B].reshape(D, B, Pw), "d",
                split_axis=0, concat_axis=0).reshape(D * B, Pw)
            recv2 = lax.all_to_all(
                b2[:D * SB].reshape(D, SB, Pw), "d",
                split_axis=0, concat_axis=0).reshape(D * SB, Pw)
            recv = jnp.concatenate([recv1, recv2])     # [R, Pw]
            gkeys = recv[:, :K]
            gcand = recv[:, K:K + PW]
            gsrc = recv[:, K + PW]
            gvalid = gkeys[:, 0] == 0
            # routed rows are mine by construction; invalid slots keep
            # the sorts-last key shape
            gkeys = jnp.where(gvalid[:, None], gkeys, invalid_key)
            return (gkeys, gcand, gsrc, spill_local, a2a_ovf,
                    maxdest_local, gvalid)

        return route_a2a, R, B, SB

    def _exchange_bytes(self, C: int, B: int, SB: int) -> int:
        """Whole-mesh bytes moved by one level's exchange (host-side,
        from the static shapes): a2a moves D*(B+SB) payload rows of
        K+PW+1 words per device; gather replicates C candidate+key rows
        to every device."""
        D, K, PW = self.D, self.K, self.PW
        if self.exchange == "a2a":
            return D * D * (B + SB) * (K + PW + 1) * 4
        return D * D * C * (K + PW) * 4

    def _merge_fn(self, SC: int, R: int,
                  VC: Optional[int] = None) -> Callable:
        """The shard-local merge-dedup shared by both step builders:
        (seen_keys [SC,K], seen_count scalar, gkeys [R,K], gcand [R,PW],
        gsrc [R]) -> dict(seen2, seen_count2, front_rows, front_rows_u,
        front_src, front_count, new_count, v_ovf, v_need).

        Two strategies, bit-identical counts/traces (ISSUE 10, pinned
        by tests): "rank" (default) shares bfs._rank_merge — the seen
        shard's sorted-prefix invariant means only the ≤R incoming keys
        are sorted per level; "fullsort" (JAXMC_MESH_RANKMERGE=0) is
        the PR-8 full stable sort over [SC+R, K+1] keys.  Both report
        seen_count2 as the TRUE per-shard need BEFORE any [:SC] crop,
        so the resident loop's grow-and-rerun path is strategy-blind;
        both leave constraint-discarded states fingerprinted but never
        counted, checked, or explored (TLC semantics).

        `VC` (rank only, ISSUE 11): the valid-candidate capacity — the
        exchanged block is ~95% masked padding, and the 5-key sort
        over all R rows DOMINATED the measured merge wall
        (MULTICHIP_r07: 11.6s of a 25s step wall on transfer_scaled
        D=1).  The rank merge now compacts the valid rows to a
        [VC]-bounded block first (cumsum-rank scatter, order
        preserved) and sorts/searches/scatters only that.  Overflow
        (`v_ovf`, with `v_need` the true count) rolls the level back
        so the caller can grow VC and redo — same contract as every
        other mesh capacity."""
        if self.merge == "rank":
            return self._merge_rank_fn(SC, R, VC)
        return self._merge_fullsort_fn(SC, R)

    def _merge_finish_fn(self, R: int):
        """Shared merge epilogue: constraint-mask the compacted new
        rows and compact the explore-kept ones to the frontier front.
        Constraints FIRST: violating states stay fingerprinted in the
        seen shard but are discarded — not distinct, not checked, not
        explored (TLC semantics, testout2:265).

        The compaction is a cumsum-rank scatter since ISSUE 11 (the
        1-key stable sort it replaces was a measurable slice of the
        merge wall at mesh shapes); the kept-row ORDER is identical —
        cumsum ranks preserve the input order exactly like the stable
        sort did — and the tail is SENTINEL rows / -0 src, which every
        consumer already masks by front_count."""
        plan = self.plan
        con_fns = self.constraint_fns
        inv_fns = self.inv_fns

        def finish(new_rows, new_src, nvalid):
            new_rows_u = plan.unpack_rows(new_rows) \
                if (con_fns or inv_fns) else new_rows
            explore = nvalid
            for nm, f in con_fns:
                explore = explore & jax.vmap(f)(new_rows_u)
            idx4 = jnp.arange(R, dtype=jnp.int32)
            pos = jnp.cumsum(explore.astype(jnp.int32)) - 1
            tgt = jnp.where(explore, pos, R + idx4)
            front_rows = jnp.full((R, new_rows.shape[1]), SENTINEL,
                                  jnp.int32) \
                .at[tgt].set(new_rows, mode="drop", unique_indices=True)
            front_rows_u = jnp.full((R, new_rows_u.shape[1]), SENTINEL,
                                    jnp.int32) \
                .at[tgt].set(new_rows_u, mode="drop",
                             unique_indices=True)
            front_src = jnp.zeros((R,), jnp.int32) \
                .at[tgt].set(new_src, mode="drop", unique_indices=True)
            front_count = jnp.sum(explore)
            return front_rows, front_rows_u, front_src, front_count

        return finish

    def _merge_rank_fn(self, SC: int, R: int,
                       VC: Optional[int] = None) -> Callable:
        """O(new) rank-merge (ISSUE 10 tentpole; ISSUE 11 made it
        O(valid) too): compact the valid exchanged rows to a
        [VC]-bounded block (cumsum-rank scatter — stable, so candidate
        order and therefore counts/traces are bit-identical), then
        sort only those keys, dedup against the sorted seen prefix
        with binary searches and scatter the new keys at their ranks —
        the single-chip resident engine's merge (bfs._rank_merge),
        shared rather than duplicated.  Sort work no longer scales
        with the seen shard OR the ~95%-padding candidate block;
        single-key-safe ops only, so the superstep while_loop can wrap
        it.  VC=None (or >= R) disables the compaction."""
        K, PW = self.K, self.PW
        compact = VC is not None and VC < R
        N = VC if compact else R
        finish = self._merge_finish_fn(N)

        def merge(seen_keys, seen_count, gkeys, gcand, gsrc):
            v_ovf = jnp.asarray(False)
            v_need = jnp.asarray(0, jnp.int32)
            if compact:
                gvalid = gkeys[:, 0] == 0
                v_need = jnp.sum(gvalid, dtype=jnp.int32)
                v_ovf = v_need > VC
                pos = jnp.cumsum(gvalid.astype(jnp.int32)) - 1
                # invalid rows park at R+i: distinct, >= VC (dropped),
                # and disjoint from every valid pos (pos <= R-1) even
                # when v_need > VC — duplicate indices, dropped or
                # not, would break the unique_indices promise below
                tgt = jnp.where(gvalid, pos,
                                R + jnp.arange(R, dtype=jnp.int32))
                ck = jnp.full((VC, K), SENTINEL, jnp.int32)
                ck = ck.at[:, 0].set(1)  # empty slots: validity lane 1
                gkeys = ck.at[tgt].set(gkeys, mode="drop",
                                       unique_indices=True)
                gcand = jnp.full((VC, PW), SENTINEL, jnp.int32) \
                    .at[tgt].set(gcand, mode="drop",
                                 unique_indices=True)
                gsrc = jnp.zeros((VC,), jnp.int32) \
                    .at[tgt].set(gsrc, mode="drop", unique_indices=True)
            rm = _rank_merge(seen_keys, seen_count, gkeys, N, SC, K,
                             multikey=True)
            new_count = rm["new_count"]
            nvalid = jnp.arange(N) < new_count
            safe = jnp.clip(rm["nk_sidx"], 0, N - 1)
            new_rows = jnp.take(gcand, safe, axis=0)
            new_src = jnp.take(gsrc, safe)
            new_rows = jnp.where(nvalid[:, None], new_rows, SENTINEL)
            front_rows, front_rows_u, front_src, front_count = \
                finish(new_rows, new_src, nvalid)
            return dict(seen2=rm["seen2"],
                        seen_count2=rm["seen_count2"],
                        front_rows=front_rows, front_rows_u=front_rows_u,
                        front_src=front_src, front_count=front_count,
                        new_count=new_count, v_ovf=v_ovf, v_need=v_need)

        return merge

    def _initial_vc(self, FC: int) -> Optional[int]:
        """The rank merge's starting valid-candidate capacity (ISSUE
        11): the learned profile value when one exists, else 4*FC —
        generously above the typical valid-row count routed to one
        shard (revisits included), so most runs never pay the growth
        redo, while staying far under R's ~95% padding.  Always >= FC
        (the committed frontier is cropped to [FC] from the compacted
        block).  JAXMC_MESH_VC pins it (growth still applies);
        JAXMC_MESH_VC=off disables the compaction entirely."""
        env = os.environ.get("JAXMC_MESH_VC", "").strip().lower()
        if env == "off":
            return None
        if env:
            try:
                return max(FC, _pow2_at_least(int(env), lo=64))
            except ValueError:
                pass
        hint = int(self._mesh_caps_hint.get("VC", 0))
        if hint:
            # a learned profile records the OBSERVED need (pow2-rounded
            # at save): trust it instead of flooring at 4*FC — the
            # whole point of the compaction is sorting the ~FC valid
            # rows, not R's padding, and an underestimate only costs
            # one growth redo
            return max(FC, _pow2_at_least(hint, lo=256))
        return max(FC, _pow2_at_least(4 * FC, lo=256))

    def _merge_out_rows(self, R: int, VC: Optional[int]) -> int:
        """Row count of the merge's compacted output block: VC when the
        rank strategy's valid-compaction is active, else R."""
        if self.merge == "rank" and VC is not None and VC < R:
            return VC
        return R

    def _merge_fullsort_fn(self, SC: int, R: int) -> Callable:
        """The PR-8 full-sort merge (JAXMC_MESH_RANKMERGE=0 escape
        hatch): one stable [SC+R, K+1]-key sort with the seen-first
        flag tiebreaker, then stable compactions.  The seen INPUT is
        masked to its valid prefix [0:seen_count) and the OUTPUT tail
        re-masked invalid, so the shard always satisfies the rank
        strategy's sorted-valid-prefix invariant (a checkpoint written
        by either strategy resumes under the other) and stale tail
        rows can never re-enter the occupancy count."""
        K = self.K
        finish = self._merge_finish_fn(R)
        invalid_key_np = np.concatenate(
            [np.ones(1, np.int32), np.full(K - 1, SENTINEL, np.int32)])

        def merge(seen_keys, seen_count, gkeys, gcand, gsrc):
            invalid_key = jnp.asarray(invalid_key_np)
            srow_valid = jnp.arange(SC) < seen_count
            seen_keys = jnp.where(srow_valid[:, None], seen_keys,
                                  invalid_key)
            allk = jnp.concatenate([seen_keys, gkeys])    # [SC+R, K]
            flag = jnp.concatenate([jnp.zeros(SC, jnp.int32),
                                    jnp.ones(R, jnp.int32)])
            idx0 = jnp.arange(SC + R, dtype=jnp.int32)
            ops = tuple(allk[:, i] for i in range(K)) + (flag, idx0)
            sorted_ = lax.sort(ops, num_keys=K + 1, is_stable=True)
            skeys = jnp.stack(sorted_[:K], axis=1)
            sflag = sorted_[K]
            perm = sorted_[K + 1]
            cidx = perm - SC              # candidate position (<0: seen)
            rvalid = skeys[:, 0] == 0
            neq_prev = jnp.concatenate([
                jnp.array([True]),
                jnp.any(skeys[1:] != skeys[:-1], axis=1)])
            new = (sflag == 1) & rvalid & neq_prev
            new_count = jnp.sum(new)

            # compact the new rows (gather payload by sorted position);
            # new_src is each new row's GLOBAL candidate index (gsrc
            # lane) — the provenance the host needs for traces
            ops2 = ((1 - new.astype(jnp.int32)), cidx)
            comp = lax.sort(ops2, num_keys=1, is_stable=True)
            new_cidx = comp[1][:R]
            safe = jnp.clip(new_cidx, 0, R - 1)
            new_rows = jnp.take(gcand, safe, axis=0)
            new_src = jnp.take(gsrc, safe)
            nvalid = jnp.arange(R) < new_count
            new_rows = jnp.where(nvalid[:, None], new_rows, SENTINEL)

            # merged seen keys, compacted (keeps key order).  NOTE
            # seen_count2 counts BEFORE the [:SC] crop, so it reports
            # the TRUE per-shard need — the resident loop grows SC to
            # exactly this on overflow
            keep = ((sflag == 0) & rvalid) | new
            ops3 = ((1 - keep.astype(jnp.int32)),) + \
                tuple(skeys[:, i] for i in range(K))
            comp3 = lax.sort(ops3, num_keys=1, is_stable=True)
            seen2 = jnp.stack(comp3[1:], axis=1)[:SC]
            seen_count2 = jnp.sum(keep)
            out_valid = jnp.arange(SC) < seen_count2
            seen2 = jnp.where(out_valid[:, None], seen2, invalid_key)

            front_rows, front_rows_u, front_src, front_count = \
                finish(new_rows, new_src, nvalid)
            return dict(seen2=seen2, seen_count2=seen_count2,
                        front_rows=front_rows, front_rows_u=front_rows_u,
                        front_src=front_src, front_count=front_count,
                        new_count=new_count,
                        # uniform surface with the rank strategy: the
                        # fullsort merge has no valid-candidate cap
                        v_ovf=jnp.asarray(False),
                        v_need=jnp.asarray(0, jnp.int32))

        return merge

    def _inv_scan(self, front_rows_u, front_count, R: int):
        """Named invariants: index of the FIRST cfg invariant any kept
        row violates, plus the first violating slot."""
        frontvalid = jnp.arange(R) < front_count
        inv_which = jnp.int32(_BIG)
        inv_slot = jnp.int32(-1)
        for i, (nm, f) in enumerate(self.inv_fns):
            bad = frontvalid & ~jax.vmap(f)(front_rows_u)
            anyb = jnp.any(bad)
            hit = anyb & (inv_which == _BIG)
            inv_which = jnp.where(hit, jnp.int32(i), inv_which)
            inv_slot = jnp.where(hit,
                                 jnp.argmax(bad).astype(jnp.int32),
                                 inv_slot)
        return inv_which, inv_slot

    def _shard_map(self):
        try:
            from jax import shard_map
        except ImportError:  # older jax
            from jax.experimental.shard_map import shard_map
        return shard_map

    def _get_mesh_step(self, SC: int, FC: int,
                       out_cap: Optional[int] = None) -> Callable:
        """The LEGACY exchange step: out_cap=None drives the host-loop
        modes (refinement/temporal PROPERTYs — _run_hostloop); out_cap
        set is the MULTI-HOST variant (tpu/multihost.py): the new
        frontier is cropped on device to a fixed [out_cap] shard so the
        host never needs non-addressable remote rows, and extra
        REPLICATED flags (psum'd over the DCN+ICI axis) are appended to
        the outputs: any_inv, fixed_ovf (a frontier/seen shard outgrew
        its fixed capacity, incl. a2a bucket+spill overflow), any_dead,
        any_assert."""
        C = self.A * FC
        route, R, B, SB = self._route_fn(C, FC)
        key = (SC, FC, B, SB, out_cap)
        if key in self._mesh_step_cache:
            return self._mesh_step_cache[key]
        K, D, PW = self.K, self.D, self.PW
        plan = self.plan
        con_fns = self.constraint_fns
        block_fn = self._candidate_block_fn(FC)
        merge_fn = self._merge_fn(SC, R)
        # refinement/temporal PROPERTYs: stream every exchanged
        # candidate (revisits included) to the host, which runs the SAME
        # stepwise refinement and behavior-graph checkers as the
        # single-chip device modes (r4; closes VERDICT r3 #9)
        need_edges = (out_cap is None and
                      (bool(self.refiners) or self.collect_edges))

        def device_step(seen_keys, seen_count, frontier_p, fcount):
            # per-device blocks: seen_keys [SC,K], seen_count [1],
            # frontier [FC,PW], fcount [1]
            seen_keys = seen_keys.reshape(SC, K)
            frontier = plan.unpack_rows(frontier_p.reshape(FC, PW))
            me = lax.axis_index("d")
            fvalid = jnp.arange(FC) < fcount[0]
            blk = block_fn(frontier, fvalid)
            overflow = blk["overflow"]
            dead = blk["dead"]
            dead_local = jnp.any(dead)
            dead_slot = blk["dead_slot"]
            assert_bad = blk["assert_bad"]
            asrt_a, asrt_f = blk["asrt_a"], blk["asrt_f"]
            gen_local = blk["gen_local"]

            (gkeys, gcand, gsrc, spill_local, a2a_ovf, _maxdest,
             evalid) = route(blk["ckeys"], blk["cand"], blk["cvalid"],
                             me)

            mg = merge_fn(seen_keys, seen_count[0], gkeys, gcand, gsrc)
            seen2 = mg["seen2"]
            seen_count2 = mg["seen_count2"]
            front_rows = mg["front_rows"]
            front_rows_u = mg["front_rows_u"]
            front_src = mg["front_src"]
            front_count = mg["front_count"]
            inv_which, inv_slot = self._inv_scan(front_rows_u,
                                                 front_count, R)

            # global totals over ICI; violation flags stay PER-DEVICE so
            # the host can locate the offending device's row/provenance
            tot_gen = lax.psum(gen_local, "d")
            tot_new = lax.psum(front_count, "d")
            any_ovf = lax.pmax(overflow, "d")  # 0 = none, else max OV_*
            tot_front = lax.psum(front_count, "d")
            tot_spill = lax.psum(spill_local, "d")

            any_a2a_ovf = lax.psum(a2a_ovf.astype(jnp.int32), "d") > 0
            if out_cap is not None:
                # multi-host: fixed-capacity frontier shard + replicated
                # abort flags — the host loop reads ONLY replicated
                # scalars and its own addressable shards. a2a bucket+
                # spill overflow folds into the fixed-capacity abort
                # (the multi-host loop cannot re-run a level, so it
                # aborts loudly instead of retrying with a larger
                # gamma).
                fixed_ovf = lax.psum(
                    ((front_count > out_cap) | (seen_count2 > SC) |
                     a2a_ovf).astype(jnp.int32), "d") > 0
                any_inv = lax.psum(
                    (inv_which != _BIG).astype(jnp.int32), "d") > 0
                any_dead = lax.psum(
                    dead_local.astype(jnp.int32), "d") > 0
                any_assert = lax.psum(
                    assert_bad.astype(jnp.int32), "d") > 0
                # indices 0-11 are the r4 surface; 12-19 add PER-DEVICE
                # provenance (each process reads only its own shards) so
                # the multi-host loop can assemble exact counterexample
                # traces via the process-allgather protocol
                # (multihost.py, VERDICT r4 #7); 20 is the psum'd spill
                # row count (ISSUE 8)
                return (seen2.reshape(1, SC, K), seen_count2.reshape(1),
                        front_rows[:out_cap].reshape(1, out_cap, PW),
                        front_count.reshape(1),
                        tot_gen.reshape(1), tot_new.reshape(1),
                        any_ovf.reshape(1), tot_front.reshape(1),
                        fixed_ovf.reshape(1), any_inv.reshape(1),
                        any_dead.reshape(1), any_assert.reshape(1),
                        front_src[:out_cap].reshape(1, out_cap),
                        inv_which.reshape(1), inv_slot.reshape(1),
                        dead_local.reshape(1), dead_slot.reshape(1),
                        assert_bad.reshape(1), asrt_a.reshape(1),
                        asrt_f.reshape(1), tot_spill.reshape(1))
            out = (seen2.reshape(1, SC, K), seen_count2.reshape(1),
                   front_rows.reshape(1, R, PW), front_count.reshape(1),
                   front_src.reshape(1, R),
                   tot_gen.reshape(1), tot_new.reshape(1),
                   dead_local.reshape(1), dead_slot.reshape(1),
                   assert_bad.reshape(1), asrt_a.reshape(1),
                   asrt_f.reshape(1), any_ovf.reshape(1),
                   inv_which.reshape(1), inv_slot.reshape(1),
                   tot_front.reshape(1), any_a2a_ovf.reshape(1),
                   tot_spill.reshape(1))
            if need_edges:
                # every exchanged candidate row + its explore mask +
                # global source index — the host-side edge stream.
                # gather mode: identical on every device (host reads
                # device 0); a2a: each device holds its own bucket.
                # `evalid` is the PRE-ownership validity from the
                # route: gkeys is already masked to owner-local rows,
                # and recomputing validity from it would silently drop
                # foreign-owned edges from the device-0 read
                # (review r8).
                exp_all = evalid
                gcand_u = plan.unpack_rows(gcand)
                for nm, f in con_fns:
                    exp_all = exp_all & jax.vmap(f)(gcand_u)
                out = out + (gcand.reshape(1, R, PW),
                             exp_all.reshape(1, R),
                             gsrc.reshape(1, R))
            return out

        shard_map = self._shard_map()
        n_out = 21 if out_cap is not None else \
            (21 if need_edges else 18)
        step = obs.prof_wrap("mesh.level_step", jax.jit(shard_map(
            device_step, mesh=self.mesh,
            in_specs=(P("d"), P("d"), P("d"), P("d")),
            out_specs=tuple([P("d")] * n_out))))
        self._mesh_step_cache[key] = step
        return step

    def _mk_level_tail(self, SC: int, FC: int, TRL: int, N: int,
                       route: Callable, merge_fn: Callable,
                       with_trace: bool) -> Callable:
        """Everything a resident level does AFTER expansion — route,
        merge-dedup, invariant scan, capacity verdicts, commit-or-
        rollback, trace-ring append, the scalar/aux vectors and the
        stop verdict — as one closure shared by the fused resident
        superstep and the grouped-expansion step (ISSUE 11), so the
        two cannot drift.  Runs inside a shard_map'd device function;
        expansion hands it the candidate block plus the per-device
        fault scalars."""

        por_plan = self._por_plan() if self.por else None
        if por_plan is not None:
            por_inst = jnp.asarray(por_plan["inst_arm"])
            por_safe_v = jnp.asarray(por_plan["arm_safe"])
        A, D, K, C = self.A, self.D, self.K, self.A * FC

        def tail(seen_keys, seen_count, frontier_p, fcount,
                 tr_rows, tr_src, lvl, dist, max_states, me,
                 ckeys, cand, cvalid, gen_local, overflow,
                 dead_local, dead_slot, assert_bad, asrt_a, asrt_f):
            # ---- device POR (ISSUE 18): the ample mask runs BEFORE the
            # exchange, against the PRE-LEVEL seen snapshot — the same
            # rule as the single-chip level/resident engines, so reduced
            # counts are bit-identical across engine shapes.  Every key
            # lives in exactly ONE owner shard: gather all devices'
            # candidate keys, probe the LOCAL shard, psum the verdicts —
            # global membership with no host round-trip, and masked rows
            # never enter the a2a/gather exchange (they also shrink the
            # ICI traffic the reduction is meant to save).
            pora = porx = porm = jnp.int32(0)
            if por_plan is not None:
                allk = lax.all_gather(ckeys, "d")         # [D, C, K]
                fl, _ = _seen_probe(seen_keys, seen_count,
                                    allk.reshape(D * C, K), SC)
                fg = lax.psum(fl.astype(jnp.int32), "d").reshape(D, C)
                found = lax.dynamic_slice_in_dim(fg, me, 1, 0)[0] > 0
                keep, pora, porx = _por_mask(
                    found, cvalid, por_inst, por_safe_v, A, FC)
                porm = jnp.sum(cvalid & ~keep, dtype=jnp.int32)
                inv_key = jnp.concatenate([
                    jnp.ones((C, 1), jnp.int32),
                    jnp.full((C, K - 1), SENTINEL, jnp.int32)], axis=1)
                ckeys = jnp.where(keep[:, None], ckeys, inv_key)
                cand = jnp.where(keep[:, None], cand, SENTINEL)
                cvalid = keep
                gen_local = gen_local - porm

            (gkeys, gcand, gsrc, spill_local, a2a_ovf, maxdest,
             _evalid) = route(ckeys, cand, cvalid, me)

            mg = merge_fn(seen_keys, seen_count, gkeys, gcand, gsrc)
            front_rows = mg["front_rows"]
            front_count = mg["front_count"]
            front_src = mg["front_src"]
            seen_count2 = mg["seen_count2"]
            inv_which, inv_slot = self._inv_scan(mg["front_rows_u"],
                                                 front_count, N)

            # ---- capacity verdicts (replicated) ----
            f_ovf = lax.psum((front_count > FC).astype(jnp.int32),
                             "d") > 0
            s_ovf = lax.psum((seen_count2 > SC).astype(jnp.int32),
                             "d") > 0
            t_ovf = (jnp.asarray(with_trace) & (lvl >= TRL)) \
                if with_trace else jnp.asarray(False)
            any_a2a_ovf = lax.psum(a2a_ovf.astype(jnp.int32),
                                   "d") > 0
            v_ovf = lax.psum(mg["v_ovf"].astype(jnp.int32),
                             "d") > 0
            grow = f_ovf | s_ovf | t_ovf | any_a2a_ovf | v_ovf
            commit = ~grow

            # ---- commit or roll back the device state ----
            seen_out = jnp.where(commit, mg["seen2"], seen_keys)
            seen_count_out = jnp.where(commit, seen_count2,
                                       seen_count)
            new_frontier = front_rows[:FC]  # N >= FC (VC clamp /
            #                                 a2a floors)
            # ring src rows keep the documented -1-means-empty
            # convention: slots past front_count hold compaction
            # leftovers (nonnegative), and an unmasked write would
            # make _ring_levels' occupied-prefix trim inert
            # (review r8)
            new_src_fc = jnp.where(
                jnp.arange(FC) < front_count,
                front_src[:FC], -1).astype(jnp.int32)
            frontier_out = jnp.where(commit, new_frontier,
                                     frontier_p)
            fcount_out = jnp.where(commit, front_count, fcount)
            if with_trace:
                wl = jnp.clip(lvl, 0, TRL - 1)
                tr_rows2 = lax.dynamic_update_slice(
                    tr_rows, new_frontier[None], (wl, 0, 0))
                tr_src2 = lax.dynamic_update_slice(
                    tr_src, new_src_fc[None], (wl, 0))
                tr_rows_out = jnp.where(commit, tr_rows2, tr_rows)
                tr_src_out = jnp.where(commit, tr_src2, tr_src)
            else:
                tr_rows_out = tr_src_out = None

            # ---- the per-level scalar vector (replicated) ----
            tot_new = lax.psum(front_count, "d")
            ovc = lax.pmax(overflow, "d")
            tot_dead = lax.psum(dead_local.astype(jnp.int32), "d")
            tot_assert = lax.psum(
                assert_bad.astype(jnp.int32), "d")
            inv_min = lax.pmin(inv_which, "d")
            scal = jnp.zeros((_NS,), jnp.int32)
            scal = scal.at[_S_GEN].set(
                lax.psum(gen_local, "d"))
            scal = scal.at[_S_NEW].set(tot_new)
            scal = scal.at[_S_FRONT].set(tot_new)
            scal = scal.at[_S_MAXF].set(lax.pmax(front_count, "d"))
            scal = scal.at[_S_MAXS].set(lax.pmax(seen_count2, "d"))
            scal = scal.at[_S_SUMS].set(lax.psum(seen_count2, "d"))
            scal = scal.at[_S_OVC].set(ovc)
            scal = scal.at[_S_DEAD].set(tot_dead)
            scal = scal.at[_S_ASSERT].set(tot_assert)
            scal = scal.at[_S_INVMIN].set(inv_min)
            scal = scal.at[_S_FOVF].set(f_ovf.astype(jnp.int32))
            scal = scal.at[_S_SOVF].set(s_ovf.astype(jnp.int32))
            scal = scal.at[_S_TOVF].set(t_ovf.astype(jnp.int32))
            scal = scal.at[_S_AOVF].set(
                any_a2a_ovf.astype(jnp.int32))
            scal = scal.at[_S_SPILL].set(
                lax.psum(spill_local, "d"))
            scal = scal.at[_S_MAXDEST].set(lax.pmax(maxdest, "d"))
            scal = scal.at[_S_VOVF].set(v_ovf.astype(jnp.int32))
            scal = scal.at[_S_MAXV].set(
                lax.pmax(mg["v_need"], "d"))
            scal = scal.at[_S_PORA].set(lax.psum(pora, "d"))
            scal = scal.at[_S_PORX].set(lax.psum(porx, "d"))
            scal = scal.at[_S_PORM].set(lax.psum(porm, "d"))

            # per-device localization vector (fetched only on
            # violation — always the LAST executed level's, because
            # every violation stops the superstep)
            aux = jnp.zeros((_NA,), jnp.int32)
            aux = aux.at[_A_INVW].set(inv_which)
            aux = aux.at[_A_INVSLOT].set(inv_slot)
            aux = aux.at[_A_DEAD].set(dead_local.astype(jnp.int32))
            aux = aux.at[_A_DEADSLOT].set(dead_slot)
            aux = aux.at[_A_ASSERT].set(
                assert_bad.astype(jnp.int32))
            aux = aux.at[_A_ASRTA].set(asrt_a)
            aux = aux.at[_A_ASRTF].set(asrt_f)

            # ---- superstep exit verdict (replicated) ----
            dist2 = jnp.where(commit, dist + tot_new, dist)
            viol = (inv_min != _BIG) | (tot_dead > 0) | \
                (tot_assert > 0) | (ovc != 0)
            trunc = commit & (max_states > 0) & \
                (dist2 >= max_states)
            done = commit & (tot_new == 0)
            stop = grow | viol | trunc | done
            lvl2 = jnp.where(commit, lvl + 1, lvl)
            return (seen_out, seen_count_out, frontier_out,
                    fcount_out, tr_rows_out, tr_src_out, lvl2,
                    dist2, scal, aux, stop)

        return tail

    def _mesh_resident_key(self, SC: int, FC: int, TRL: int,
                           VC: Optional[int]):
        """The resident step's compile-cache key — shared with the run
        loop's fresh_compile detection so the two can never disagree."""
        C = self.A * FC
        B = self._a2a_bucket(C, FC) if self.exchange == "a2a" else 0
        SB = self._a2a_spill_bucket(B) if B else 0
        return ("grp" if self._grouped else "res", SC, FC, TRL, B, SB,
                self.store_trace, self.merge, VC)

    def _get_mesh_resident_step(self, SC: int, FC: int, TRL: int,
                                VC: Optional[int] = None) -> Callable:
        """The MESH-RESIDENT superstep (ISSUE 8 tentpole, ISSUE 10
        multi-level fusion): one jitted shard_map dispatch that runs UP
        TO `maxlvl` levels in a lax.while_loop — each level expands,
        exchanges, merge-dedups against the seen shards and appends the
        per-level trace ring IN PLACE — and returns the full device
        state plus a device-side RING of per-level scalar vectors the
        host drains once per superstep (the only thing it reads on the
        clean path).  The loop exits early on violation / deadlock /
        assert / kernel overflow / truncation / empty frontier, and on
        any capacity overflow (seen / frontier / trace ring / a2a
        bucket+spill) the offending level rolls back inside the step
        (its outputs == its inputs), so rollback, violation
        localization, drain and checkpointing keep their exact
        one-level-per-dispatch semantics.

        maxlvl, the level budget per dispatch, is a TRACED argument
        (like the single-chip resident maxlvl) so the host adapts it
        without recompiling.  The "fullsort" merge strategy cannot live
        inside a while_loop (multi-key sort comparators explode XLA
        compile time there), so it compiles the single-level body
        applied once — the one-level-per-dispatch escape-hatch program
        — with the identical ring-of-one output surface.

        Many-instance models on XLA:CPU (self._grouped) get the
        GROUPED-expansion variant instead: same signature, same
        outputs, expansion split into arm-group dispatches (ISSUE
        11)."""
        if self._grouped:
            return self._get_mesh_grouped_step(SC, FC, TRL, VC)
        C = self.A * FC
        route, R, B, SB = self._route_fn(C, FC)
        with_trace = self.store_trace
        superstep = self.merge == "rank"
        key = self._mesh_resident_key(SC, FC, TRL, VC)
        if key in self._mesh_step_cache:
            return self._mesh_step_cache[key]
        K, D, PW = self.K, self.D, self.PW
        plan = self.plan
        block_fn = self._candidate_block_fn(FC)
        merge_fn = self._merge_fn(SC, R, VC)
        # N: the merge's compacted output block (VC when the rank
        # valid-compaction is active) — the shapes every post-merge
        # consumer (inv scan, frontier crop) runs at
        N = self._merge_out_rows(R, VC)
        check_deadlock = self.model.check_deadlock

        def device_step(seen_keys, seen_count, frontier_p, fcount,
                        *rest):
            if with_trace:
                tr_rows = rest[0].reshape(TRL, FC, PW)
                tr_src = rest[1].reshape(TRL, FC)
                lvl0, maxlvl, dist0, max_states = rest[2:]
            else:
                tr_rows = tr_src = None
                lvl0, maxlvl, dist0, max_states = rest
            seen_keys = seen_keys.reshape(SC, K)
            frontier_p = frontier_p.reshape(FC, PW)
            seen_count0 = seen_count[0]
            fcount0 = fcount[0]
            me = lax.axis_index("d")

            tail = self._mk_level_tail(SC, FC, TRL, N, route, merge_fn,
                                       with_trace)

            def one_level(seen_keys, seen_count, frontier_p, fcount,
                          tr_rows, tr_src, lvl, dist):
                """One BFS level (the PR-8 step body): returns the
                committed-or-rolled-back state, the level's scalar
                vector, the localization vector, and the replicated
                stop verdict.  Expansion here, everything after it in
                the SHARED level tail (_mk_level_tail — the grouped
                expansion step runs the same tail, so the two step
                shapes cannot drift)."""
                frontier = plan.unpack_rows(frontier_p)
                fvalid = jnp.arange(FC) < fcount
                blk = block_fn(frontier, fvalid)
                dead_local = (jnp.any(blk["dead"]) if check_deadlock
                              else jnp.asarray(False))
                return tail(seen_keys, seen_count, frontier_p, fcount,
                            tr_rows, tr_src, lvl, dist, max_states, me,
                            blk["ckeys"], blk["cand"], blk["cvalid"],
                            blk["gen_local"], blk["overflow"],
                            dead_local, blk["dead_slot"],
                            blk["assert_bad"], blk["asrt_a"],
                            blk["asrt_f"])

            ring0 = jnp.zeros((_SS_RINGCAP, _NS), jnp.int32)
            aux0 = jnp.zeros((_NA,), jnp.int32)

            if superstep:
                # one body serves both trace configurations: without
                # tracing the two trace-ring carry slots hold scalar
                # dummies that thread through unchanged (while_loop
                # carries need consistent pytrees; one_level never
                # touches its tr args when with_trace is False)
                def body(carry):
                    (sk, sc_, fp, fc_, trr, trs, lvl, dist, nlv, ring,
                     aux, stop) = carry
                    (sk, sc_, fp, fc_, trr2, trs2, lvl, dist, scal,
                     aux, stop) = one_level(
                        sk, sc_, fp, fc_,
                        trr if with_trace else None,
                        trs if with_trace else None, lvl, dist)
                    if with_trace:
                        trr, trs = trr2, trs2
                    ring = lax.dynamic_update_slice(ring, scal[None],
                                                    (nlv, 0))
                    return (sk, sc_, fp, fc_, trr, trs, lvl, dist,
                            nlv + 1, ring, aux, stop)

                def cond(carry):
                    nlv, stop = carry[8], carry[11]
                    return (~stop) & (nlv < jnp.minimum(
                        maxlvl, jnp.int32(_SS_RINGCAP)))

                dummy = jnp.int32(0)
                carry0 = (seen_keys, seen_count0, frontier_p, fcount0,
                          tr_rows if with_trace else dummy,
                          tr_src if with_trace else dummy,
                          lvl0, dist0, jnp.int32(0), ring0, aux0,
                          jnp.asarray(False))
                carry = lax.while_loop(cond, body, carry0)
                (seen_f, seen_count_f, frontier_f, fcount_f) = carry[:4]
                tr_rows_f, tr_src_f = (carry[4], carry[5]) \
                    if with_trace else (None, None)
                nlv_f, ring_f, aux_f = carry[8], carry[9], carry[10]
            else:
                # fullsort escape hatch: the identical body, applied
                # once outside any while_loop — a ring of one entry
                (seen_f, seen_count_f, frontier_f, fcount_f, tr_rows_f,
                 tr_src_f, _lvl, _dist, scal, aux_f, _stop) = one_level(
                    seen_keys, seen_count0, frontier_p, fcount0,
                    tr_rows, tr_src, lvl0, dist0)
                ring_f = lax.dynamic_update_slice(ring0, scal[None],
                                                  (0, 0))
                nlv_f = jnp.int32(1)

            outs = [seen_f.reshape(1, SC, K),
                    seen_count_f.reshape(1),
                    frontier_f.reshape(1, FC, PW),
                    fcount_f.reshape(1)]
            if with_trace:
                outs.append(tr_rows_f.reshape(1, TRL, FC, PW))
                outs.append(tr_src_f.reshape(1, TRL, FC))
            outs.append(ring_f.reshape(1, _SS_RINGCAP, _NS))
            outs.append(nlv_f.reshape(1))
            outs.append(aux_f.reshape(1, _NA))
            return tuple(outs)

        shard_map = self._shard_map()
        n_in = 10 if with_trace else 8
        n_out = 9 if with_trace else 7
        in_specs = tuple([P("d")] * (n_in - 4)) + (P(), P(), P(), P())
        # donate the big device buffers — seen, frontier, trace ring —
        # so XLA updates them in place across levels (accelerators;
        # XLA:CPU ignores donation with a warning, JAXMC_DONATE forces)
        donate = ((0, 2, 4, 5) if with_trace else (0, 2)) \
            if self.donate else ()
        # check_rep=False: shard_map's replication checker has no rule
        # for lax.while_loop (the superstep level loop); every output
        # is P("d")-sharded anyway, so nothing relied on inferred
        # replication
        step = obs.prof_wrap("mesh.superstep", jax.jit(shard_map(
            device_step, mesh=self.mesh,
            in_specs=in_specs,
            out_specs=tuple([P("d")] * n_out),
            check_rep=False),
            donate_argnums=donate))
        self._mesh_step_cache[key] = step
        return step

    def _mesh_expand_group_jits(self, FC: int):
        """The arm-group expansion dispatches for the grouped mesh
        level (ISSUE 11): contiguous groups of compiled actions, each
        holding at most fused_max kernel INSTANCES (one slotted kernel
        counts its slot fan-out, exactly like bfs._hstep_groups), each
        group one shard_map'd jit over the mesh.  Returns (jits,
        offsets): offsets[g] is group g's first flat instance index —
        concatenating group candidate blocks in order reproduces the
        fused expansion's [A*FC] candidate order bit-for-bit."""
        ckey = ("grpexp", FC)
        if ckey in self._mesh_step_cache:
            return self._mesh_step_cache[ckey]
        K, PW, W = self.K, self.PW, self.W
        plan = self.plan
        keys_of = self._keys_of
        shard_map = self._shard_map()
        fused_max = self._mesh_fused_max
        # independence-driven group plan (ISSUE 15) shared with the
        # bfs host_seen path; inst_blocks carry each group's original
        # flat instance indices so the caller can restore provenance
        # order after the group dispatches
        gplan = self._arm_group_plan(fused_max)
        groups = [[self.compiled[i] for i in g] for g in gplan]
        inst_blocks = self._group_inst_blocks(gplan)

        def _mk(subset):
            ag = sum(max(1, ca.n_slots) for ca in subset)
            Cg = ag * FC

            def gdev(frontier_p, fcount):
                frontier = plan.unpack_rows(frontier_p.reshape(FC, PW))
                fvalid = jnp.arange(FC) < fcount[0]
                ens, aoks, ovs, succs = [], [], [], []
                for ca in subset:
                    if ca.n_slots:
                        slots = jnp.arange(ca.n_slots, dtype=jnp.int32)
                        en, aok, ov, succ = jax.vmap(
                            jax.vmap(ca.fn, in_axes=(0, None)),
                            in_axes=(None, 0))(frontier, slots)
                        for si in range(ca.n_slots):
                            ens.append(en[si])
                            aoks.append(aok[si])
                            ovs.append(ov[si])
                            succs.append(succ[si])
                    else:
                        en, aok, ov, succ = jax.vmap(ca.fn)(frontier)
                        ens.append(en)
                        aoks.append(aok)
                        ovs.append(ov)
                        succs.append(succ)
                en = jnp.stack(ens)            # [ag, FC]
                aok = jnp.stack(aoks)
                ov = jnp.stack(ovs)
                succ = jnp.stack(succs)        # [ag, FC, W]
                valid = en & fvalid[None, :]
                abad = (~aok) & fvalid[None, :]
                ov_g = jnp.max(jnp.where(fvalid[None, :], ov, 0)) \
                    .astype(jnp.int32)
                gen_g = jnp.sum(valid)
                cand_u = succ.reshape(Cg, W)
                cvalid = valid.reshape(Cg)
                cand_u = jnp.where(cvalid[:, None], cand_u, SENTINEL)
                ckeys, cand, pack_ovf = keys_of(cand_u, cvalid)
                return (ckeys.reshape(1, Cg, K),
                        cand.reshape(1, Cg, PW),
                        cvalid.reshape(1, Cg),
                        jnp.any(en, axis=0).reshape(1, FC),
                        gen_g.reshape(1), ov_g.reshape(1),
                        jnp.any(abad).reshape(1),
                        jnp.argmax(abad.reshape(-1))
                        .astype(jnp.int32).reshape(1),
                        pack_ovf.reshape(1))

            return obs.prof_wrap("mesh.group_expand", jax.jit(shard_map(
                gdev, mesh=self.mesh, in_specs=(P("d"), P("d")),
                out_specs=tuple([P("d")] * 9))))

        jits = [_mk(g) for g in groups]
        obs.current().gauge("mesh.grouped_expand", len(jits))
        out = (jits, inst_blocks)
        self._mesh_step_cache[ckey] = out
        return out

    def _get_mesh_grouped_step(self, SC: int, FC: int, TRL: int,
                               VC: Optional[int] = None) -> Callable:
        """The grouped-expansion resident level (ISSUE 11): expansion
        as ceil(A/fused_max) group dispatches (host-combined fault
        scalars, numpy), then ONE merge/tail dispatch running the
        SHARED level tail — same call signature and output surface as
        the fused resident step, so the run loop cannot tell them
        apart.  Always one level per dispatch (self._ss_fixed == 1).
        No buffer donation: the frontier feeds every group dispatch
        AND the tail, and this path is XLA:CPU-gated anyway."""
        C = self.A * FC
        route, R, B, SB = self._route_fn(C, FC)
        with_trace = self.store_trace
        key = self._mesh_resident_key(SC, FC, TRL, VC)
        if key in self._mesh_step_cache:
            return self._mesh_step_cache[key]
        K, D, PW = self.K, self.D, self.PW
        merge_fn = self._merge_fn(SC, R, VC)
        N = self._merge_out_rows(R, VC)
        check_deadlock = self.model.check_deadlock
        tail = self._mk_level_tail(SC, FC, TRL, N, route, merge_fn,
                                   with_trace)
        jits, inst_blocks = self._mesh_expand_group_jits(FC)
        # provenance restore (ISSUE 15): regrouped dispatches emit
        # candidates in group order; one gather puts them back into
        # original instance order so counts/traces stay byte-identical
        inst_order = np.concatenate(inst_blocks) if inst_blocks \
            else np.zeros(0, np.int64)
        identity_order = bool(
            (inst_order == np.arange(self.A)).all())
        pos = np.empty(self.A, np.int64)
        pos[inst_order] = np.arange(self.A)
        cand_perm = (pos[:, None] * FC
                     + np.arange(FC)[None, :]).reshape(-1)
        max_ag = max((len(b) for b in inst_blocks), default=1)
        inst_pad = np.zeros((max(len(inst_blocks), 1), max_ag),
                            np.int64)
        for _gi, _b in enumerate(inst_blocks):
            inst_pad[_gi, :len(_b)] = _b

        def tail_dev(seen_keys, seen_count, frontier_p, fcount, *rest):
            if with_trace:
                tr_rows = rest[0].reshape(TRL, FC, PW)
                tr_src = rest[1].reshape(TRL, FC)
                rest = rest[2:]
            else:
                tr_rows = tr_src = None
            (ckeys, cand, cvalid, gen_local, ov_local, dead_local,
             dead_slot, assert_local, asrt_a, asrt_f, lvl, dist,
             max_states) = rest
            me = lax.axis_index("d")
            (seen_f, seen_count_f, frontier_f, fcount_f, trr, trs,
             _lvl2, _dist2, scal, aux, _stop) = tail(
                seen_keys.reshape(SC, K), seen_count[0],
                frontier_p.reshape(FC, PW), fcount[0],
                tr_rows, tr_src, lvl, dist, max_states, me,
                ckeys.reshape(C, K), cand.reshape(C, PW),
                cvalid.reshape(C), gen_local[0], ov_local[0],
                dead_local[0], dead_slot[0], assert_local[0],
                asrt_a[0], asrt_f[0])
            ring = lax.dynamic_update_slice(
                jnp.zeros((_SS_RINGCAP, _NS), jnp.int32),
                scal[None], (0, 0))
            outs = [seen_f.reshape(1, SC, K), seen_count_f.reshape(1),
                    frontier_f.reshape(1, FC, PW), fcount_f.reshape(1)]
            if with_trace:
                outs.append(trr.reshape(1, TRL, FC, PW))
                outs.append(trs.reshape(1, TRL, FC))
            outs.append(ring.reshape(1, _SS_RINGCAP, _NS))
            outs.append(jnp.ones((1,), jnp.int32))  # nlv: one level
            outs.append(aux.reshape(1, _NA))
            return tuple(outs)

        shard_map = self._shard_map()
        n_shard = (16 if with_trace else 14)
        n_out = 9 if with_trace else 7
        jtail = obs.prof_wrap("mesh.grouped_tail", jax.jit(shard_map(
            tail_dev, mesh=self.mesh,
            in_specs=tuple([P("d")] * n_shard) + (P(), P(), P()),
            out_specs=tuple([P("d")] * n_out),
            check_rep=False)))

        def step(seen, seen_count, frontier, fcount, *args):
            if with_trace:
                tr = (args[0], args[1])
                lvl0, _maxlvl, dist0, max_states = args[2:]
            else:
                tr = ()
                lvl0, _maxlvl, dist0, max_states = args
            outs = [jf(frontier, fcount) for jf in jits]
            ckeys = jnp.concatenate([o[0] for o in outs], axis=1)
            cand = jnp.concatenate([o[1] for o in outs], axis=1)
            cvalid = jnp.concatenate([o[2] for o in outs], axis=1)
            if not identity_order:
                permj = jnp.asarray(cand_perm, jnp.int32)
                ckeys = jnp.take(ckeys, permj, axis=1)
                cand = jnp.take(cand, permj, axis=1)
                cvalid = jnp.take(cvalid, permj, axis=1)
            # host-combined per-device fault scalars (tiny [D] reads):
            # exactly what the fused step's block_fn computes inline
            en_any = np.logical_or.reduce(
                [np.asarray(o[3]) for o in outs])        # [D, FC]
            gen_local = np.sum([np.asarray(o[4]) for o in outs],
                               axis=0).astype(np.int32)
            ovmax = np.max([np.asarray(o[5]) for o in outs],
                           axis=0).astype(np.int32)
            povf = np.logical_or.reduce(
                [np.asarray(o[8]) != 0 for o in outs])   # pack guard
            ov_local = np.where(
                ovmax != 0, ovmax,
                np.where(povf, OV_PACK, 0)).astype(np.int32)
            fcnt = np.asarray(fcount)
            fvalid = np.arange(FC)[None, :] < fcnt[:, None]
            dead = fvalid & ~en_any
            if check_deadlock:
                dead_local = dead.any(axis=1)
            else:
                dead_local = np.zeros(D, bool)
            dead_slot = dead.argmax(axis=1).astype(np.int32)
            aa = np.stack([np.asarray(o[6]) != 0 for o in outs])
            af = np.stack([np.asarray(o[7]) for o in outs])
            assert_local = aa.any(axis=0)
            # pick the asserting row FIRST IN ORIGINAL instance order
            # (the fused step's argmax semantics) across the groups:
            # per-group first-assert rows map through inst_pad back to
            # original flat indices, then min-reduce
            g_arange = np.arange(aa.shape[0])[:, None]
            orig_flat = inst_pad[g_arange, af // FC] * FC + af % FC
            orig_flat = np.where(aa, orig_flat, np.int64(2 ** 62))
            sel = orig_flat.min(axis=0)                      # [D]
            asrt_a = np.where(assert_local, sel // FC,
                              0).astype(np.int32)
            asrt_f = np.where(assert_local, sel % FC,
                              0).astype(np.int32)
            targs = (seen, seen_count, frontier, fcount) + tr + (
                ckeys, cand, cvalid,
                jnp.asarray(gen_local), jnp.asarray(ov_local),
                jnp.asarray(dead_local), jnp.asarray(dead_slot),
                jnp.asarray(assert_local), jnp.asarray(asrt_a),
                jnp.asarray(asrt_f),
                lvl0, dist0, max_states)
            return jtail(*targs)

        self._mesh_step_cache[key] = step
        return step

    def _init_shards(self, init_rows: np.ndarray, explored_idx,
                     D: int, SC: int, FC: int,
                     keys=None, packed=None, owner=None):
        """Host-side initial shard construction shared by the
        single-controller run() and the multi-host loop
        (tpu/multihost.py): per-owner frontier fill and lexsorted seen
        keys with the validity-lane-1 empty-slot convention. One layout
        rule, so host and device dedup can never diverge. Returns
        (seen [D,SC,K], frontier [D,FC,PW], fcount [D],
        seen_counts [D]) as numpy — the per-shard valid-prefix lengths
        the merge strategies key on, returned here so no caller
        re-derives them from the validity lane."""
        K = self.K
        if keys is None:
            keys, packed, povf = self._host_keys(init_rows)
            if povf:
                from ..compile.vspec import CompileError
                raise CompileError(self._pack_ovf_msg())
            owner = self._owner_from_keys(keys)
        exp = np.zeros(len(init_rows), bool)
        exp[np.asarray(explored_idx, int)] = True
        frontier = np.full((D, FC, self.PW), SENTINEL, np.int32)
        seen = np.full((D, SC, K), SENTINEL, np.int32)
        seen[:, :, 0] = 1  # empty slots: validity lane 1
        fcount = np.zeros((D,), np.int32)
        seen_counts = np.zeros((D,), np.int32)
        for d in range(D):
            p = packed[(owner == d) & exp]
            frontier[d, :len(p)] = p
            k = keys[owner == d]
            if len(k):
                order = np.lexsort(tuple(k[:, i]
                                         for i in reversed(range(K))))
                seen[d, :len(k)] = k[order]
            fcount[d] = len(p)
            seen_counts[d] = len(k)
        return seen, frontier, fcount, seen_counts

    # ---- trace reconstruction (host side) ----
    #
    # self._levels[L] = (rows [D, cap_L, W] np, src [D, cap_L] np | None).
    # Level 0 holds the initial frontier (src None). For L >= 1, slot i on
    # device d holds global candidate index g = src[d][i]; with C_L =
    # A * FC_L (the expanding level's capacity): source device g // C_L,
    # candidate c = g % C_L, action c // FC_L, parent slot c % FC_L.
    # The resident loop materializes _levels lazily from the device
    # trace ring (one pull, only on a violation or checkpoint).

    def _mesh_trace_to(self, dev: int, slot: int, depth: int,
                       extra: Optional[Tuple[Dict, str]] = None):
        if not self.store_trace:
            return None
        out = []
        d, i = dev, slot
        for lvl in range(depth, -1, -1):
            rows, src, FC = self._levels[lvl]
            st = self.layout.decode_packed(np.asarray(rows[d][i]))
            if lvl == 0:
                out.append((st, "Initial predicate"))
            else:
                g = int(src[d][i])
                C = self.A * FC
                a = (g % C) // FC
                out.append((st, self.labels_flat[a]))
                d, i = g // C, (g % C) % FC
        out.reverse()
        if extra is not None:
            out.append(extra)
        return out

    def _mesh_refine_edges(self, frontier_np, ecand, eexp, esrc,
                           FC, depth):
        """Stepwise refinement over this level's explored candidate
        edges — the host runs the SAME checkers as the single-chip
        modes, with parents resolved through the global source index
        (g -> source device, action, frontier slot)."""
        C = self.A * FC
        idxs = np.nonzero(eexp)[0]
        if not len(idxs):
            return None
        parents: Dict[Tuple[int, int], dict] = {}
        if len(self._ref_pair_cache) > (1 << 20):
            self._ref_pair_cache.clear()
        for c in idxs:
            g = int(esrc[c])
            d_src, cc = g // C, g % C
            a, f = cc // FC, cc % FC
            key = (frontier_np[d_src, f].tobytes(), ecand[c].tobytes())
            if key in self._ref_pair_cache:
                continue
            self._ref_pair_cache.add(key)
            pst = parents.get((d_src, f))
            if pst is None:
                pst = self.layout.decode_packed(frontier_np[d_src, f])
                parents[(d_src, f)] = pst
            sst = self.layout.decode_packed(ecand[c])
            for rc in self.refiners:
                if not rc.check_edge(pst, sst):
                    trace = self._mesh_trace_to(
                        d_src, f, depth,
                        extra=(sst, self.labels_flat[a]))
                    return self._viol("property", rc.name, trace,
                                      self._refine_msg(rc))
        return None

    def _viol(self, kind, name, trace, msg=None):
        if trace is None:
            note = (f"{kind} found (mesh traces disabled by "
                    f"store_trace=False)")
            return Violation(kind, name, [], msg or note)
        return Violation(kind, name, trace, msg)

    # ---- checkpoint/resume (level boundaries) ----

    def _mesh_ck(self, seen, seen_counts, frontier, fcount, FC, SC,
                 depth, generated, distinct):
        self._write_ck(
            "mesh", D=self.D, FC=FC, SC=SC, depth=depth,
            generated=generated, distinct=distinct,
            seen=np.asarray(seen), seen_counts=np.asarray(seen_counts),
            frontier=np.asarray(frontier), fcount=np.asarray(fcount),
            levels=self._levels if self.store_trace else None)

    def run(self) -> CheckResult:
        # the edge stream feeds refiners and non-[]P liveness; []P-only
        # obligations still need the behavior-graph STATES (per-level
        # kept rows), so the mode guards key on the wider condition
        need_edges = bool(self.refiners) or self.collect_edges
        need_props = bool(self.refiners) or bool(self.live_obligations)
        # per-RUN accounting: the final gauges (_mk) must describe THIS
        # run — a warm re-run (bench timed window) must not inherit the
        # warm-up's spill/bucket peaks (review r8).  Learned caps and
        # gamma deliberately persist on the instance.
        self._spill_rows = 0
        self._max_bucket = 0
        self._shard_balance = None
        self._supersteps = 0
        self._superstep_levels_max = 0
        self._ss_shrunk = False
        # chosen strategy + gamma, once per run (ISSUE 8 satellite)
        resident = not (need_props or need_edges or
                        os.environ.get("JAXMC_MESH_RESIDENT", "1")
                        == "0")
        self.log(f"-- mesh: {self.D} device(s), exchange="
                 f"{self.exchange} ({self._exchange_src}), "
                 f"gamma={self._a2a_gamma:g}, merge={self.merge}, "
                 f"loop={'resident' if resident else 'host'}"
                 + (" [mesh_skew fault armed]" if self._skew else ""))
        tel = obs.current()
        tel.gauge("mesh.exchange", self.exchange)
        tel.gauge("mesh.devices", self.D)
        # the mesh engine's own strategy stamps (ISSUE 10 satellite):
        # TpuExplorer.__init__ gauges dedup.mode BEFORE the mesh
        # subclass forces fp128 keys, so multichip artifacts carried a
        # stale (or, under serve/bench telemetry scoping, no) value —
        # re-stamp both here so `obs report` highlights name the dedup
        # and merge strategy that actually ran
        tel.gauge("dedup.mode",
                  "fp128" + ("-view" if self.view_fn is not None
                             else ("-packed" if not self.plan.identity
                                   else "")))
        # likewise seen.mode (ISSUE 12): the base constructor stamped
        # it before the mesh subclass forced fp128 keys
        tel.gauge("seen.mode", "fingerprint")
        tel.gauge("mesh.merge", self.merge)
        if resident:
            return self._run_mesh_resident()
        if self.seen_cap is not None:
            # the legacy host loop (refinement/temporal PROPERTYs)
            # keeps the historical grow-forever behavior: name it
            # instead of silently ignoring the cap
            self.log("-- mesh host loop: --seen-cap/JAXMC_SEEN_CAP is "
                     "ignored here (tier spill runs on the resident "
                     "mesh loop; refinement/temporal PROPERTYs force "
                     "the host loop)")
        return self._run_hostloop(need_edges, need_props)

    # ------------------------------------------------------------------
    # the MESH-RESIDENT loop (ISSUE 8 tentpole)
    # ------------------------------------------------------------------

    def _pad_dev(self, arr, axis: int, newdim: int, fill: int,
                 lane1: bool = False):
        """Grow a [D, ...] device array along `axis` with constant fill
        (validity-lane-1 empty-slot convention for seen shards)."""
        shape = list(arr.shape)
        shape[axis] = newdim - shape[axis]
        pad = np.full(shape, fill, np.int32)
        if lane1:
            pad[..., 0] = 1
        return jnp.concatenate([arr, jnp.asarray(pad)], axis=axis)

    def _ring_levels(self, tr_rows, tr_src, upto: int) -> None:
        """Materialize self._levels[1..upto] from the device trace ring
        — the ONE row pull a violating/checkpointing resident run pays
        (mesh.row_syncs)."""
        if not self.store_trace or upto <= 0:
            return
        tel = obs.current()
        tel.counter("mesh.row_syncs")
        rows_np = np.asarray(tr_rows)   # [D, TRL, FC, PW]
        src_np = np.asarray(tr_src)     # [D, TRL, FC]
        del self._levels[1:]
        for l in range(upto):
            # trim to the occupied prefix (src == -1 marks empty slots)
            occ = np.nonzero((src_np[:, l] >= 0).any(axis=0))[0]
            keep = int(occ.max()) + 1 if len(occ) else 1
            self._levels.append((rows_np[:, l, :keep].copy(),
                                 src_np[:, l, :keep].copy(),
                                 self._lvl_FC[l]))

    def _run_mesh_resident(self) -> CheckResult:
        t0 = time.time()
        tel = obs.current()
        model = self.model
        D, K, PW = self.D, self.K, self.PW
        warnings = ["mesh backend: dedup on 128-bit fingerprints; "
                    "collision probability < n^2 * 2^-129"]
        warnings.extend(self._temporal_warnings())
        warnings.extend(self._symmetry_warnings())
        warnings.extend(self._por_warnings())

        init_rows, explored_init, n_init, err = \
            self._prepare_init(t0, warnings)
        if err is not None:
            return err
        generated = n_init
        explored_mask = np.zeros(n_init, bool)
        explored_mask[explored_init] = True
        distinct = int(explored_mask.sum())

        self._levels: List[Tuple[np.ndarray, Optional[np.ndarray], int]] \
            = []
        self._lvl_FC = []
        hint = self._mesh_caps_hint

        if self.resume_from:
            ck = self._load_ck("mesh")
            if ck["D"] != D:
                raise ValueError(
                    f"cannot resume: checkpoint has {ck['D']} devices, "
                    f"mesh has {D}")
            FC = max(ck["FC"], _pow2_at_least(
                int(hint.get("FC", 1)), lo=64))
            SC = max(ck["SC"], _pow2_at_least(
                int(hint.get("SC", 1)), lo=256))
            depth = ck["depth"]
            generated = ck["generated"]
            distinct = ck["distinct"]
            seen_np = np.full((D, SC, K), SENTINEL, np.int32)
            seen_np[:, :, 0] = 1
            seen_np[:, :ck["SC"]] = ck["seen"]
            seen = jnp.asarray(seen_np)
            seen_count = jnp.asarray(
                ck["seen_counts"].astype(np.int32))
            fr_np = np.full((D, FC, PW), SENTINEL, np.int32)
            fr_np[:, :ck["FC"]] = ck["frontier"]
            frontier = jnp.asarray(fr_np)
            fcount = jnp.asarray(ck["fcount"].astype(np.int32))
            if ck.get("levels") is not None:
                self._levels = list(ck["levels"])
            elif self.store_trace:
                # advisor r3: match _restore_ck_state — a user expecting
                # traces must hear it up front, not get an empty-trace
                # violation later
                raise ValueError(
                    "cannot resume with traces: the checkpoint was "
                    "written with --no-trace")
            self._lvl_FC = [lv[2] for lv in self._levels[1:]]
            TRL = _pow2_at_least(
                max(depth + 1, int(hint.get("TRL", 1)), 16), lo=16)
            self.log(f"Resuming mesh run at depth {depth} "
                     f"({distinct} distinct states)")
        else:
            init_keys, init_packed, init_povf = \
                self._host_keys(init_rows)
            if init_povf:
                from ..compile.vspec import CompileError
                raise CompileError(self._pack_ovf_msg())
            owner = self._owner_from_keys(init_keys)
            per_dev = [init_rows[(owner == d) & explored_mask]
                       for d in range(D)]
            FC = _pow2_at_least(
                max(max((len(p) for p in per_dev), default=1), 1,
                    int(hint.get("FC", 1))), lo=64)
            SC = _pow2_at_least(max(4 * FC, int(hint.get("SC", 1))),
                                lo=256)
            shard_cap = self._mesh_shard_cap()
            if shard_cap is not None:
                # device seen cap (ISSUE 12): bound each shard's hot
                # tier from the start, floored so every shard seats
                # its init keys (a too-small cap soft-breaches)
                SC = min(SC, shard_cap)
                SC = max(SC, _pow2_at_least(
                    max(int(np.bincount(owner, minlength=D).max()), 1),
                    lo=64))
            TRL = _pow2_at_least(max(int(hint.get("TRL", 1)), 16),
                                 lo=16)
            explored_idx = np.nonzero(explored_mask)[0]
            seen_np, frontier_np, fcount_np, scount_np = \
                self._init_shards(
                    init_rows, explored_idx, D, SC, FC,
                    keys=init_keys, packed=init_packed, owner=owner)
            if self.store_trace:
                self._levels.append((frontier_np.copy(), None, FC))
            seen = jnp.asarray(seen_np)
            frontier = jnp.asarray(frontier_np)
            fcount = jnp.asarray(fcount_np.astype(np.int32))
            seen_count = jnp.asarray(scount_np)
            depth = 0

        tr_rows = tr_src = None
        if self.store_trace:
            ring_np = np.full((D, TRL, FC, PW), SENTINEL, np.int32)
            src_np_ = np.full((D, TRL, FC), -1, np.int32)
            for l, (rows, src, _fcl) in enumerate(self._levels[1:]):
                k = min(rows.shape[1], FC)
                ring_np[:, l, :k] = rows[:, :k]
                src_np_[:, l, :k] = src[:, :k]
            tr_rows = jnp.asarray(ring_np)
            tr_src = jnp.asarray(src_np_)
            # _levels beyond the init level will be re-materialized from
            # the ring on demand; keep only level 0 host-side
            del self._levels[1:]

        last_progress = last_ck = time.time()
        lvl_frontier = int(np.sum(np.asarray(fcount)))
        # rank-merge valid-candidate capacity (ISSUE 11): starts at the
        # learned/heuristic value, grows by rollback-and-redo exactly
        # like SC/FC/TRL when a level's valid exchanged rows outgrow it
        VC = self._initial_vc(FC)
        # superstep controller (ISSUE 10): JAXMC_MESH_SUPERSTEP pins
        # the level budget per dispatch; auto starts at the learned
        # warm value (1 on a cold engine — the first dispatch is
        # exactly the one-level program run) and adapts to measured
        # dispatch wall so progress, checkpoint and drain attention
        # keep their cadence, like the single-chip resident maxlvl
        # controller (tpu/bfs.py)
        maxlvl = self._ss_fixed or min(self._mesh_maxlvl_warm,
                                       _SS_RINGCAP)
        target_s = max(1.0, min(
            self.progress_every or 30.0,
            (self.checkpoint_every or 1e9) if self.checkpoint_path
            else 1e9))
        while lvl_frontier > 0:
            lvl_t0 = time.time()
            # chaos sites: crash / drain between dispatches — with
            # supersteps these are SUPERSTEP boundaries, the only
            # host-attention points the resident mesh loop has
            # (jaxmc/faults.py)
            faults.kill_self("run_kill", level=depth, engine="mesh")
            faults.inject("device_run_fail", level=depth, engine="mesh")
            if self._drain_requested(warnings, "mesh"):
                if self.checkpoint_path:
                    self._ring_levels(tr_rows, tr_src, depth)
                    self._mesh_ck(seen, np.asarray(seen_count),
                                  frontier, fcount, FC, SC, depth,
                                  generated, distinct)
                return self._mk(True, distinct, generated, depth, t0,
                                warnings, truncated=True, drained=True)

            C = self.A * FC
            B = self._a2a_bucket(C, FC) if self.exchange == "a2a" else 0
            SB = self._a2a_spill_bucket(B) if B else 0
            step_key = self._mesh_resident_key(SC, FC, TRL, VC)
            fresh_compile = step_key not in self._mesh_step_cache
            step = self._get_mesh_resident_step(SC, FC, TRL, VC)
            # HBM model (ISSUE 17): the sharded tables at their current
            # (possibly re-grown) capacities, summed over the D devices
            obs.note_buffer("mesh.seen_shards", D * SC * K * 4)
            obs.note_buffer("mesh.frontier", D * FC * PW * 4)
            if self.store_trace:
                obs.note_buffer("mesh.trace_ring",
                                D * TRL * FC * (PW + 1) * 4)
            args = (seen, seen_count, frontier, fcount)
            if self.store_trace:
                args = args + (tr_rows, tr_src)
            # once spilled (ISSUE 12) every level needs a cold-tier
            # probe at the host boundary: pin supersteps to one level
            eff_maxlvl = 1 if (self._tiers is not None
                               and self._tiers.active) else maxlvl
            args = args + (jnp.int32(depth), jnp.int32(eff_maxlvl),
                           jnp.int32(distinct),
                           jnp.int32(self.max_states or 0))
            outs = step(*args)
            if self.store_trace:
                (seen2, seen_count2, frontier2, fcount2, tr_rows2,
                 tr_src2, ring_d, nlv_d, aux_d) = outs
            else:
                (seen2, seen_count2, frontier2, fcount2, ring_d,
                 nlv_d, aux_d) = outs
                tr_rows2 = tr_src2 = None
            # THE one host sync of the superstep: the replicated
            # per-level scalar ring + its occupancy (every per-device
            # row is identical; tiny).  mesh.host_syncs therefore
            # counts SUPERSTEPS, not levels (obs/schema.py PR-10).
            ring = np.asarray(ring_d)[0]
            nlv = max(1, int(np.asarray(nlv_d)[0]))
            disp_wall = time.time() - lvl_t0
            tel.counter("mesh.host_syncs")
            tel.counter("mesh.exchange_bytes",
                        self._exchange_bytes(C, B, SB) * nlv)
            self._supersteps += 1
            self._superstep_levels_max = max(self._superstep_levels_max,
                                             nlv)
            # adopt the device state: levels before a rolled-back or
            # violating level committed inside the dispatch, the
            # offending level itself rolled back (outputs == inputs)
            seen, seen_count = seen2, seen_count2
            frontier, fcount = frontier2, fcount2
            if self.store_trace:
                tr_rows, tr_src = tr_rows2, tr_src2
            # adapt the level budget toward the host-attention target;
            # a dispatch that just paid an XLA recompile is not
            # evidence about execution speed — skip it.  The warm
            # value tracks the SETTLED budget (it follows halvings
            # down), not the running max: a budget the controller
            # judged too slow must not come back on warm runs, where
            # it would stall drain/checkpoint attention for the whole
            # oversized dispatch (review r10)
            if self._ss_fixed is None:
                if fresh_compile:
                    pass
                elif disp_wall > 1.5 * target_s and maxlvl > 1:
                    maxlvl = max(1, maxlvl // 2)
                    self._ss_shrunk = True
                elif disp_wall < target_s / 4 and maxlvl < _SS_RINGCAP:
                    maxlvl = min(_SS_RINGCAP, maxlvl * 2)
                self._mesh_maxlvl_warm = maxlvl
            lwall = round(disp_wall / nlv, 6)

            # ---- drain the ring: one record per executed level, the
            # exact PR-8 one-level host sequence replayed per entry ----
            for li in range(nlv):
                scal = ring[li]
                fresh = fresh_compile and li == 0
                ovc = int(scal[_S_OVC])
                if ovc:
                    if ovc == OV_DEMOTED:
                        msg = ("a demoted compile-recovery fired (the "
                               "kernel under-approximates here): run "
                               "the host_seen mode, which demotes the "
                               "arm to the interpreter and restarts — "
                               "raising caps cannot help")
                    elif ovc == OV_PACK:
                        msg = self._pack_ovf_msg()
                    else:
                        msg = ("a container exceeded its lane capacity "
                               f"({self._caps_note()}); counts would "
                               "no longer be exact")
                    return self._mk(False, distinct, generated, depth,
                                    t0, warnings, Violation(
                                        "error", "capacity overflow",
                                        [], msg))

                if scal[_S_FOVF] or scal[_S_SOVF] or scal[_S_TOVF] or \
                        scal[_S_AOVF] or scal[_S_VOVF]:
                    # the step rolled this level back on device (and
                    # stopped the superstep, so it is the ring's LAST
                    # entry): grow every flagged capacity at once
                    # (each growth recompiles the step, so batching
                    # growths minimizes recompiles), then redo the
                    # level in the next dispatch
                    grew = []
                    if scal[_S_AOVF]:
                        # grow gamma straight to the OBSERVED per-peer
                        # need (the max bucket occupancy rode the
                        # scalar vector) instead of blind doubling:
                        # one rerun covers even pathological skew, and
                        # the spill bucket keeps absorbing
                        # between-level drift afterwards
                        need_g = int(scal[_S_MAXDEST]) * self.D \
                            / max(C, 1)
                        self._a2a_gamma = max(self._a2a_gamma * 2,
                                              need_g)
                        grew.append(f"gamma->{self._a2a_gamma:g}")
                    if scal[_S_SOVF]:
                        SC2 = _pow2_at_least(int(scal[_S_MAXS]),
                                             lo=2 * SC)
                        shard_cap = self._mesh_shard_cap()
                        scounts_now = np.asarray(seen_count)
                        if shard_cap is not None and SC2 > shard_cap \
                                and scounts_now.sum() > 0:
                            # per-shard device tier full (ISSUE 12):
                            # spill every shard's sorted prefix to the
                            # cold tiers and redo the level against
                            # empty shards instead of growing past the
                            # cap
                            seen, seen_count = self._mesh_tier_spill(
                                seen, seen_count, SC)
                            grew.append(
                                f"seen->tier-spill("
                                f"{int(scounts_now.sum())} keys, "
                                f"host={self._tiers.host_keys} "
                                f"disk={self._tiers.disk_keys})")
                        else:
                            seen = self._pad_dev(seen, 1, SC2, SENTINEL,
                                                 lane1=True)
                            SC = SC2
                            grew.append(f"SC->{SC}")
                    if scal[_S_FOVF]:
                        FC2 = _pow2_at_least(int(scal[_S_MAXF]),
                                             lo=2 * FC)
                        frontier = self._pad_dev(frontier, 1, FC2,
                                                 SENTINEL)
                        if self.store_trace:
                            tr_rows = self._pad_dev(tr_rows, 2, FC2,
                                                    SENTINEL)
                            tr_src = self._pad_dev(tr_src, 2, FC2, -1)
                        FC = FC2
                        grew.append(f"FC->{FC}")
                    if scal[_S_TOVF]:
                        TRL2 = _pow2_at_least(depth + 1, lo=2 * TRL)
                        tr_rows = self._pad_dev(tr_rows, 1, TRL2,
                                                SENTINEL)
                        tr_src = self._pad_dev(tr_src, 1, TRL2, -1)
                        TRL = TRL2
                        grew.append(f"TRL->{TRL}")
                    if scal[_S_VOVF]:
                        # grow straight to the observed valid-row need
                        # (it rode the scalar vector), like gamma —
                        # pure recompile, no device buffers to pad
                        VC = max(FC, _pow2_at_least(
                            int(scal[_S_MAXV]), lo=2 * (VC or FC)))
                        grew.append(f"VC->{VC}")
                    if scal[_S_FOVF] and VC is not None:
                        # the compacted block must still cover the
                        # frontier crop after FC growth
                        VC = max(VC, FC)
                    self._remember_caps(SC, FC, TRL, VC)
                    self.log(f"-- mesh: growing {', '.join(grew)} "
                             f"(level {depth} redone)")
                    tel.level(depth, frontier=lvl_frontier, generated=0,
                              new=0, distinct=distinct, devices=D,
                              redo=",".join(grew),
                              fresh_compile=fresh,
                              wall_s=lwall)
                    break

                # committed level
                if self.store_trace:
                    self._lvl_FC.append(FC)
                self._spill_rows += int(scal[_S_SPILL])
                self._max_bucket = max(self._max_bucket,
                                       int(scal[_S_MAXDEST]))
                self._vc_seen_need = max(self._vc_seen_need,
                                         int(scal[_S_MAXV]))

                # deadlock/assert live in the CURRENT frontier (depth
                # d): totals exclude the partial level, like the host
                # loop
                if model.check_deadlock and scal[_S_DEAD]:
                    aux = np.asarray(aux_d)
                    dv = int(np.argmax(aux[:, _A_DEAD]))
                    ds = int(aux[dv, _A_DEADSLOT])
                    self._ring_levels(tr_rows, tr_src, depth)
                    trace = self._mesh_trace_to(dv, ds, depth)
                    return self._mk(False, distinct, generated, depth,
                                    t0, warnings,
                                    self._viol("deadlock", "deadlock",
                                               trace))
                if scal[_S_ASSERT]:
                    aux = np.asarray(aux_d)
                    av = int(np.argmax(aux[:, _A_ASSERT]))
                    aa = int(aux[av, _A_ASRTA])
                    af = int(aux[av, _A_ASRTF])
                    self._ring_levels(tr_rows, tr_src, depth)
                    trace = self._mesh_trace_to(av, af, depth)
                    return self._mk(
                        False, distinct, generated, depth, t0,
                        warnings,
                        self._viol("assert", "Assert", trace,
                                   f"assertion in "
                                   f"{self.labels_flat[aa]}"))

                generated += int(scal[_S_GEN])
                distinct += int(scal[_S_NEW])
                self._por_stats["ample"] += int(scal[_S_PORA])
                self._por_stats["expanded"] += int(scal[_S_PORX])
                self._por_stats["masked"] += int(scal[_S_PORM])
                sum_seen = int(scal[_S_SUMS])
                max_seen = int(scal[_S_MAXS])
                self._fp_occupancy = sum_seen
                if sum_seen:
                    self._shard_balance = max_seen / (sum_seen / D)
                tel.level(depth, frontier=lvl_frontier,
                          generated=int(scal[_S_GEN]),
                          new=int(scal[_S_NEW]), distinct=distinct,
                          seen=sum_seen, devices=D, fc=FC,
                          spill=int(scal[_S_SPILL]),
                          max_bucket=int(scal[_S_MAXDEST]),
                          superstep=self._supersteps,
                          fresh_compile=fresh,
                          wall_s=lwall)

                which = int(scal[_S_INVMIN])
                if which != _BIG:
                    # invariant violations live in the NEW frontier
                    # (depth+1); the globally LOWEST violated
                    # cfg-invariant index wins, then the first device
                    # holding it
                    aux = np.asarray(aux_d)
                    nm = self.inv_fns[which][0]
                    iv_dev = int(np.argmax(aux[:, _A_INVW] == which))
                    iv_slot = int(aux[iv_dev, _A_INVSLOT])
                    self._ring_levels(tr_rows, tr_src, depth + 1)
                    trace = self._mesh_trace_to(iv_dev, iv_slot,
                                                depth + 1)
                    return self._mk(False, distinct, generated,
                                    depth + 1, t0, warnings,
                                    self._viol("invariant", nm, trace))
                depth += 1
                lvl_frontier = int(scal[_S_FRONT])
                if self._tiers is not None and self._tiers.active and \
                        lvl_frontier > 0:
                    # cold-tier filter (ISSUE 12; supersteps pinned to
                    # 1): drop frontier rows whose keys were spilled —
                    # the rows the uncapped shards would have deduped —
                    # and rewrite the trace-ring slot to match, so the
                    # next level's parent indices keep resolving
                    (frontier, fcount, tr_rows, tr_src, n_dup) = \
                        self._mesh_tier_filter(frontier, fcount,
                                               tr_rows, tr_src,
                                               depth, FC)
                    if n_dup:
                        distinct -= n_dup
                        lvl_frontier -= n_dup
                    self._tiers.publish_gauges(sum_seen)

                if self.max_states and distinct >= self.max_states:
                    # a truncation point IS a level boundary: leave a
                    # checkpoint so the run can be resumed past the
                    # limit
                    if self.checkpoint_path:
                        self._ring_levels(tr_rows, tr_src, depth)
                        self._mesh_ck(seen, np.asarray(seen_count),
                                      frontier, fcount, FC, SC, depth,
                                      generated, distinct)
                    self._save_mesh_profile(SC, FC, TRL, VC)
                    self.log("-- state limit reached, search truncated")
                    return self._mk(
                        True, distinct, generated, depth, t0, warnings,
                        truncated=True,
                        trunc_reason=f"max_states: distinct {distinct} "
                                     f">= limit {self.max_states}")

            now = time.time()
            if now - last_progress >= self.progress_every:
                last_progress = now
                self.log(f"Progress({depth}): {generated} generated, "
                         f"{distinct} distinct, "
                         f"{lvl_frontier} on queue.")
            if self.checkpoint_path and \
                    now - last_ck >= self.checkpoint_every:
                last_ck = now
                self._ring_levels(tr_rows, tr_src, depth)
                self._mesh_ck(seen, np.asarray(seen_count), frontier,
                              fcount, FC, SC, depth, generated,
                              distinct)

        if self._ss_fixed is None and not self._ss_shrunk:
            # fast models: remember enough budget to cover the whole
            # search in ONE dispatch on a warm re-run (the early exit
            # stops at the empty frontier, so over-budget is free) —
            # but never after the controller had to shrink: a budget
            # it judged too slow must stay retired
            self._mesh_maxlvl_warm = min(
                max(depth + 1, self._mesh_maxlvl_warm), _SS_RINGCAP)
        self._save_mesh_profile(SC, FC, TRL, VC)
        if self.checkpoint_path and self.final_checkpoint:
            # COMPLETED-run checkpoint (serve warm resume): an empty
            # frontier over the full seen set
            self._ring_levels(tr_rows, tr_src, depth)
            self._mesh_ck(seen, np.asarray(seen_count),
                          jnp.asarray(np.zeros((D, FC, PW), np.int32)),
                          jnp.asarray(np.zeros(D, np.int32)),
                          FC, SC, depth, generated, distinct)
        self.log("Model checking completed. No error has been found.")
        self.log(f"{generated} states generated, {distinct} distinct "
                 f"states found, 0 states left on queue.")
        return self._mk(True, distinct, generated, depth - 1, t0,
                        warnings)

    def _remember_caps(self, SC: int, FC: int, TRL: int,
                       VC: Optional[int] = None) -> None:
        """Keep the learned caps on the INSTANCE so warm re-runs (bench
        timed windows) start at them — zero growth redos, zero
        recompiles — exactly like the single-chip resident engine's
        _res_caps."""
        h = self._mesh_caps_hint
        h["SC"] = max(int(h.get("SC", 0)), SC)
        h["FC"] = max(int(h.get("FC", 0)), FC)
        h["TRL"] = max(int(h.get("TRL", 0)), TRL)
        h["GAM16"] = max(int(h.get("GAM16", 0)),
                         int(round(self._a2a_gamma * 16)))
        # MSL is the SETTLED levels-per-dispatch, not a floor: it must
        # follow the controller down when a budget proved too slow
        h["MSL"] = max(1, int(self._mesh_maxlvl_warm))
        if VC is not None:
            h["VC"] = max(int(h.get("VC", 0)), VC)

    def _save_mesh_profile(self, SC: int, FC: int, TRL: int,
                           VC: Optional[int] = None) -> None:
        self._remember_caps(SC, FC, TRL, VC)
        caps = {"SC": SC, "FC": FC, "TRL": TRL,
                "GAM16": max(1, int(round(self._a2a_gamma * 16))),
                "MSL": max(1, int(self._mesh_maxlvl_warm))}
        if VC is not None and self._vc_seen_need:
            # persist the OBSERVED need, not the running capacity
            # (which starts at the conservative 4*FC default and only
            # grows): the next process warm-starts its merge at the
            # lean size — the ISSUE 11 merge-wall win — and at worst
            # pays one growth redo if its workload needs more.  The
            # in-process hint (_remember_caps) keeps the capacity so a
            # warm re-run in THIS process never recompiles.  Runs that
            # never observed a need (fullsort escape hatch, compaction
            # disabled) save NO VC at all — persisting the 4*FC
            # heuristic would max-merge over a learned lean value and
            # permanently inflate every future rank merge
            # (_MESH_PROFILE_OPT contract above).
            caps["VC"] = max(FC, _pow2_at_least(
                self._vc_seen_need, lo=256))
        self._save_caps_profile(
            caps, variant=self._profile_variant(),
            keys=_MESH_PROFILE_KEYS, optional=_MESH_PROFILE_OPT)

    # ------------------------------------------------------------------
    # phase-wall probe (ISSUE 10 obs satellite)
    # ------------------------------------------------------------------

    def probe_phase_walls(self, max_levels: int = 4
                          ) -> Optional[Dict[str, float]]:
        """Measured expand / exchange / merge wall breakdown.

        The fused superstep makes the hot path unobservable from the
        host (one dispatch covers many levels), so the breakdown comes
        from a PROBE: the three phases built as SEPARATE jitted
        shard_map programs at the run's learned capacities, driven a
        few levels over the real initial shards, each phase timed with
        block_until_ready (compile excluded by an untimed warm-up
        pass).  BOTH merge strategies are timed on identical inputs
        every level, so the artifact shows the rank-vs-fullsort merge
        wall directly — the merge win lands in the obs artifact, not
        just the scaling curve.  Best-effort perf probe only (stops if
        the probe outgrows its fixed caps); counts are never consumed.

        Gauges: mesh.phase_levels, mesh.phase_expand_s,
        mesh.phase_exchange_s, mesh.phase_merge_rank_s,
        mesh.phase_merge_fullsort_s, mesh.phase_merge_s (the active
        strategy's total); one `mesh.phase_walls` trace event per
        probed level."""
        tel = obs.current()
        t_all = time.time()
        init_rows, explored_init, n_init, err = \
            self._prepare_init(t_all, [])
        if err is not None:
            return None
        D, K, PW = self.D, self.K, self.PW
        hint = self._mesh_caps_hint
        explored_mask = np.zeros(n_init, bool)
        explored_mask[explored_init] = True
        FC = _pow2_at_least(
            max(int(hint.get("FC", 1)), max(1,
                                            int(explored_mask.sum()))),
            lo=64)
        SC = _pow2_at_least(max(4 * FC, int(hint.get("SC", 1))),
                            lo=256)
        seen_np, frontier_np, fcount_np, scount_np = self._init_shards(
            init_rows, np.nonzero(explored_mask)[0], D, SC, FC)
        C = self.A * FC
        route, R, B, SB = self._route_fn(C, FC)
        block_fn = self._candidate_block_fn(FC)
        plan = self.plan
        shard_map = self._shard_map()

        def expand_step(frontier_p, fcount):
            frontier = plan.unpack_rows(frontier_p.reshape(FC, PW))
            fvalid = jnp.arange(FC) < fcount[0]
            blk = block_fn(frontier, fvalid)
            return (blk["ckeys"].reshape(1, C, K),
                    blk["cand"].reshape(1, C, PW),
                    blk["cvalid"].reshape(1, C))

        def route_step(ckeys, cand, cvalid):
            me_ = lax.axis_index("d")
            gkeys, gcand, gsrc = route(ckeys.reshape(C, K),
                                       cand.reshape(C, PW),
                                       cvalid.reshape(C), me_)[:3]
            return (gkeys.reshape(1, R, K), gcand.reshape(1, R, PW),
                    gsrc.reshape(1, R))

        # the rank merge is probed WITH the engine's valid-compaction
        # capacity (ISSUE 11): probing the uncompacted path would
        # report a merge wall the real run no longer pays.  When this
        # engine has already run (the meshbench flow: warm-up + timed
        # run, then the probe), the probe builds its own jits at the
        # OBSERVED need — the size the durable profile hands the next
        # process — so the artifact reports the warm-started merge
        # wall, not the conservative first-process default.
        VCp = self._initial_vc(FC)
        if self._vc_seen_need and VCp is not None:
            VCp = max(FC, _pow2_at_least(self._vc_seen_need, lo=256))

        def mk_merge(strategy):
            if strategy == "rank":
                mfn = self._merge_rank_fn(SC, R, VCp)
            else:
                mfn = self._merge_fullsort_fn(SC, R)

            def merge_step(seen_keys, seen_count, gkeys, gcand, gsrc):
                mg = mfn(seen_keys.reshape(SC, K), seen_count[0],
                         gkeys.reshape(R, K), gcand.reshape(R, PW),
                         gsrc.reshape(R))
                return (mg["seen2"].reshape(1, SC, K),
                        mg["seen_count2"].reshape(1),
                        mg["front_rows"][:FC].reshape(1, FC, PW),
                        mg["front_count"].reshape(1),
                        mg["v_need"].reshape(1))

            return merge_step

        jexp = obs.prof_wrap("mesh.probe_expand", jax.jit(shard_map(
            expand_step, mesh=self.mesh,
            in_specs=(P("d"), P("d")), out_specs=(P("d"),) * 3)))
        jrt = obs.prof_wrap("mesh.probe_route", jax.jit(shard_map(
            route_step, mesh=self.mesh,
            in_specs=(P("d"),) * 3, out_specs=(P("d"),) * 3)))
        jmg = {s: obs.prof_wrap(f"mesh.probe_merge_{s}", jax.jit(
            shard_map(
                mk_merge(s), mesh=self.mesh,
                in_specs=(P("d"),) * 5, out_specs=(P("d"),) * 5)))
            for s in ("rank", "fullsort")}

        seen = jnp.asarray(seen_np)
        scount = jnp.asarray(scount_np)
        frontier = jnp.asarray(frontier_np)
        fcount = jnp.asarray(fcount_np.astype(np.int32))

        def timed(f, *a):
            t0 = time.time()
            out = f(*a)
            jax.block_until_ready(out)
            return out, time.time() - t0

        # untimed warm-up pass: compile all four programs once
        o1 = jexp(frontier, fcount)
        jax.block_until_ready(o1)
        o2 = jrt(*o1)
        jax.block_until_ready(o2)
        for s in jmg:
            jax.block_until_ready(jmg[s](seen, scount, *o2))

        walls = {"expand": 0.0, "exchange": 0.0,
                 "merge_rank": 0.0, "merge_fullsort": 0.0}
        lv = 0
        while lv < max_levels and int(np.sum(np.asarray(fcount))) > 0:
            o1, w_e = timed(jexp, frontier, fcount)
            walls["expand"] += w_e
            o2, w_x = timed(jrt, *o1)
            walls["exchange"] += w_x
            outs = {}
            w_m = {}
            for s in ("fullsort", "rank"):
                outs[s], w_m[s] = timed(jmg[s], seen, scount, *o2)
                walls["merge_" + s] += w_m[s]
            seen2, scount2, frontier2, fcount2, v_need2 = outs["rank"]
            tel.event("mesh.phase_walls", level=lv,
                      expand_s=round(w_e, 6), exchange_s=round(w_x, 6),
                      merge_rank_s=round(w_m["rank"], 6),
                      merge_fullsort_s=round(w_m["fullsort"], 6))
            if int(np.max(np.asarray(scount2))) > SC or \
                    int(np.max(np.asarray(fcount2))) > FC or \
                    (VCp is not None and
                     int(np.max(np.asarray(v_need2))) > VCp):
                break  # probe caps outgrown: keep what we measured
            seen, scount = seen2, scount2
            frontier, fcount = frontier2, fcount2
            lv += 1

        # the DENOMINATOR (ISSUE 11 acceptance): the real fused
        # one-level resident step, timed at the same capacities over
        # its own state — "merge+expand share of the step wall" is
        # (expand_s + merge_s) / step_s, phases and step measured by
        # the same probe.  Rebuilt from the host-side initial shards
        # because donation (accelerators) consumes the step's inputs.
        step_wall = 0.0
        step_levels = 0
        VCe = VCp if self.merge == "rank" else None
        TRLp = _pow2_at_least(max_levels + 2, lo=16)
        try:
            jstep = self._get_mesh_resident_step(SC, FC, TRLp, VCe)
            s_seen = jnp.asarray(seen_np)
            s_scnt = jnp.asarray(scount_np)
            s_front = jnp.asarray(frontier_np)
            s_fcnt = jnp.asarray(fcount_np.astype(np.int32))
            s_tr = (jnp.full((D, TRLp, FC, PW), SENTINEL, jnp.int32),
                    jnp.full((D, TRLp, FC), -1, jnp.int32)) \
                if self.store_trace else ()
            warm = True
            while step_levels < max_levels and \
                    int(np.sum(np.asarray(s_fcnt))) > 0:
                args = (s_seen, s_scnt, s_front, s_fcnt) + s_tr + (
                    jnp.int32(step_levels), jnp.int32(1),
                    jnp.int32(0), jnp.int32(0))
                souts, w_s = timed(jstep, *args)
                if warm:
                    # first call pays the compile: measure it again
                    warm = False
                    ring0 = np.asarray(souts[-3])[0]
                    if ring0[0][_S_FOVF] or ring0[0][_S_SOVF] or \
                            ring0[0][_S_TOVF] or ring0[0][_S_AOVF] or \
                            ring0[0][_S_VOVF]:
                        break  # probe caps too small for the real step
                    s_seen, s_scnt, s_front, s_fcnt = souts[:4]
                    s_tr = souts[4:6] if self.store_trace else ()
                    continue
                step_wall += w_s
                step_levels += 1
                ring = np.asarray(souts[-3])[0]
                if ring[0][_S_FOVF] or ring[0][_S_SOVF] or \
                        ring[0][_S_TOVF] or ring[0][_S_AOVF] or \
                        ring[0][_S_VOVF] or ring[0][_S_OVC]:
                    break
                s_seen, s_scnt, s_front, s_fcnt = souts[:4]
                s_tr = souts[4:6] if self.store_trace else ()
        except Exception as ex:  # noqa: BLE001 — a perf probe must
            # never fail the run it rides on
            self.log(f"-- phase probe: step timing skipped ({ex})")

        out = {"levels": lv,
               "expand_s": round(walls["expand"], 6),
               "exchange_s": round(walls["exchange"], 6),
               "merge_rank_s": round(walls["merge_rank"], 6),
               "merge_fullsort_s": round(walls["merge_fullsort"], 6)}
        out["merge_s"] = out["merge_rank_s"] if self.merge == "rank" \
            else out["merge_fullsort_s"]
        if step_levels:
            out["step_levels"] = step_levels
            out["step_s"] = round(step_wall, 6)
            # normalize to per-level before forming the share: the
            # phase loop and the step loop can cover different level
            # counts (either can hit a cap early)
            hot = (out["expand_s"] + out["merge_s"]) / max(lv, 1)
            out["hot_share"] = round(
                hot / max(step_wall / step_levels, 1e-9), 4)
        tel.gauge("mesh.phase_levels", lv)
        tel.gauge("mesh.phase_expand_s", out["expand_s"])
        tel.gauge("mesh.phase_exchange_s", out["exchange_s"])
        tel.gauge("mesh.phase_merge_s", out["merge_s"])
        tel.gauge("mesh.phase_merge_rank_s", out["merge_rank_s"])
        tel.gauge("mesh.phase_merge_fullsort_s",
                  out["merge_fullsort_s"])
        if step_levels:
            tel.gauge("mesh.phase_step_s", out["step_s"])
            tel.gauge("mesh.phase_hot_share", out["hot_share"])
        return out

    # ------------------------------------------------------------------
    # the LEGACY host loop (refinement/temporal PROPERTYs; the
    # JAXMC_MESH_RESIDENT=0 diagnosis escape hatch)
    # ------------------------------------------------------------------

    def _run_hostloop(self, need_edges: bool,
                      need_props: bool) -> CheckResult:
        t0 = time.time()
        tel = obs.current()
        model = self.model
        D, W, K = self.D, self.W, self.K
        warnings = ["mesh backend: dedup on 128-bit fingerprints; "
                    "collision probability < n^2 * 2^-129"]
        warnings.extend(self._temporal_warnings())
        if self.por and self._por_plan() is not None:
            # reachable only via the JAXMC_MESH_RESIDENT=0 escape hatch
            # (refinement/temporal PROPERTYs already refuse in
            # _por_plan): the ample mask lives in the resident
            # superstep's level tail — name the refusal, run unreduced
            self._por_memo = None
            self.por_reason = ("mesh host loop active "
                               "(JAXMC_MESH_RESIDENT=0): the device "
                               "mask lives in the resident superstep")
            obs.current().gauge("por.disabled_reason", self.por_reason)
            obs.current().gauge("por.enabled", False)
            warnings.append(f"--por requested but reduction disabled: "
                            f"{self.por_reason} (running unreduced)")
        if need_props and not self.store_trace:
            raise ModeError(
                "mesh refinement/temporal checking needs the per-level "
                "row stream: run with store_trace=True (default)")
        if need_props and self.resume_from:
            raise ModeError(
                "mesh resume with refinement/temporal PROPERTYs is not "
                "supported - use the single-chip device modes")
        warnings.extend(self._symmetry_warnings())

        init_rows, explored_init, n_init, err = \
            self._prepare_init(t0, warnings)
        if err is not None:
            return err
        generated = n_init
        explored_mask = np.zeros(n_init, bool)
        explored_mask[explored_init] = True
        distinct = int(explored_mask.sum())

        self._levels: List[Tuple[np.ndarray, Optional[np.ndarray], int]] \
            = []
        graph = None   # behavior graph (temporal PROPERTYs)
        fsids = None   # flat (d*FC + slot) -> graph state id

        if self.resume_from:
            ck = self._load_ck("mesh")
            if ck["D"] != D:
                raise ValueError(
                    f"cannot resume: checkpoint has {ck['D']} devices, "
                    f"mesh has {D}")
            FC, SC = ck["FC"], ck["SC"]
            depth = ck["depth"]
            generated = ck["generated"]
            distinct = ck["distinct"]
            seen = jnp.asarray(ck["seen"])
            seen_counts = ck["seen_counts"].astype(np.int64)
            frontier = jnp.asarray(ck["frontier"])
            fcount = jnp.asarray(ck["fcount"])
            if ck.get("levels") is not None:
                self._levels = ck["levels"]
            elif self.store_trace:
                # advisor r3: match _restore_ck_state — a user expecting
                # traces must hear it up front, not get an empty-trace
                # violation later
                raise ValueError(
                    "cannot resume with traces: the checkpoint was "
                    "written with --no-trace")
            self.log(f"Resuming mesh run at depth {depth} "
                     f"({distinct} distinct states)")
        else:
            init_keys, init_packed, init_povf = \
                self._host_keys(init_rows)
            if init_povf:
                from ..compile.vspec import CompileError
                raise CompileError(self._pack_ovf_msg())
            owner = self._owner_from_keys(init_keys)
            per_dev = [init_rows[(owner == d) & explored_mask]
                       for d in range(D)]
            FC = _pow2_at_least(
                max(max((len(p) for p in per_dev), default=1), 1), lo=64)
            SC = _pow2_at_least(4 * FC, lo=256)
            explored_idx = np.nonzero(explored_mask)[0]
            seen, frontier, fcount, init_scounts = self._init_shards(
                init_rows, explored_idx, D, SC, FC,
                keys=init_keys, packed=init_packed, owner=owner)
            if self.live_obligations:
                graph = _LiveGraph(self.labels_flat, self.collect_edges)
                graph.add_inits(init_packed, explored_idx)
                # (d, slot) -> behavior-graph state id, flat [D*FC]
                fsids = np.full(D * FC, -1, np.int64)
                for d in range(D):
                    for i in range(int(fcount[d])):
                        fsids[d * FC + i] = graph.sid_by_key[
                            frontier[d, i].tobytes()]
            if self.store_trace:
                self._levels.append((frontier.copy(), None, FC))
            frontier = jnp.asarray(frontier)
            seen = jnp.asarray(seen)
            fcount = jnp.asarray(fcount)
            seen_counts = init_scounts.astype(np.int64)
            depth = 0

        last_progress = last_ck = time.time()
        lvl_frontier = int(np.sum(np.asarray(fcount)))
        while lvl_frontier > 0:
            lvl_t0 = time.time()
            lvl_gen0 = generated
            C = self.A * FC
            need = int(seen_counts.max(initial=0)) + D * C
            if need > SC:
                SC2 = _pow2_at_least(need, SC)
                pad = np.full((D, SC2 - SC, K), SENTINEL, np.int32)
                pad[:, :, 0] = 1
                seen = jnp.concatenate([seen, jnp.asarray(pad)], axis=1)
                SC = SC2
            expanding_FC = FC
            while True:
                step = self._get_mesh_step(SC, FC)
                outs = step(seen,
                            jnp.asarray(seen_counts.astype(np.int32)),
                            frontier, fcount)
                # count THIS attempt's exchange with the gamma it ran
                # at: gamma-doubling reruns each pay a full exchange
                # (review r8)
                B_att = self._a2a_bucket(C, FC) \
                    if self.exchange == "a2a" else 0
                tel.counter("mesh.exchange_bytes", self._exchange_bytes(
                    C, B_att,
                    self._a2a_spill_bucket(B_att) if B_att else 0))
                (seen2_, seen_cnt, front_rows, front_cnt, front_src,
                 tot_gen, tot_new, dead_local, dead_slot, assert_local,
                 asrt_a, asrt_f, any_ovf, inv_which, inv_slot,
                 tot_front, a2a_ovf, tot_spill) = outs[:18]
                if self.exchange == "a2a" and \
                        bool(np.asarray(a2a_ovf)[0]):
                    # hash skew exceeded the per-peer bucket AND the
                    # spill pass: rerun the level with doubled capacity
                    # factor (inputs are untouched — the step is
                    # functional)
                    self._a2a_gamma *= 2
                    self.log(f"-- mesh: a2a bucket+spill overflow, "
                             f"gamma -> {self._a2a_gamma}")
                    continue
                seen = seen2_
                break
            self._spill_rows += int(np.asarray(tot_spill)[0])

            ovc = int(np.asarray(any_ovf)[0])
            if ovc:
                if ovc == OV_DEMOTED:
                    msg = ("a demoted compile-recovery fired (the "
                           "kernel under-approximates here): run the "
                           "host_seen mode, which demotes the arm to "
                           "the interpreter and restarts — raising "
                           "caps cannot help")
                elif ovc == OV_PACK:
                    msg = self._pack_ovf_msg()
                else:
                    msg = ("a container exceeded its lane capacity "
                           f"({self._caps_note()}); counts would no "
                           "longer be exact")
                return self._mk(False, distinct, generated, depth, t0,
                                warnings, Violation(
                                    "error", "capacity overflow", [],
                                    msg))
            dead_np = np.asarray(dead_local)
            if model.check_deadlock and dead_np.any():
                dv = int(np.argmax(dead_np))
                ds = int(np.asarray(dead_slot)[dv])
                trace = self._mesh_trace_to(dv, ds, depth)
                return self._mk(False, distinct, generated, depth, t0,
                                warnings,
                                self._viol("deadlock", "deadlock", trace))
            assert_np = np.asarray(assert_local)
            if assert_np.any():
                av = int(np.argmax(assert_np))
                aa = int(np.asarray(asrt_a)[av])
                af = int(np.asarray(asrt_f)[av])
                trace = self._mesh_trace_to(av, af, depth)
                return self._mk(
                    False, distinct, generated, depth, t0, warnings,
                    self._viol("assert", "Assert", trace,
                               f"assertion in {self.labels_flat[aa]}"))

            ecand = eexp = esrc = None
            if need_edges:
                # the exchanged candidate stream (revisits included):
                # gather mode replicates it on every device (read device
                # 0); a2a routes disjoint buckets (concatenate all)
                if self.exchange == "a2a":
                    ecand = np.asarray(outs[18]).reshape(-1, self.PW)
                    eexp = np.asarray(outs[19]).reshape(-1)
                    esrc = np.asarray(outs[20]).reshape(-1)
                else:
                    ecand = np.asarray(outs[18][0])
                    eexp = np.asarray(outs[19][0])
                    esrc = np.asarray(outs[20][0])
                if self.refiners:
                    fr_np = np.asarray(frontier)
                    rv = self._mesh_refine_edges(fr_np, ecand, eexp,
                                                 esrc, expanding_FC,
                                                 depth)
                    if rv is not None:
                        return self._mk(False, distinct, generated,
                                        depth, t0, warnings, rv)

            generated += int(np.asarray(tot_gen)[0])
            distinct += int(np.asarray(tot_new)[0])
            seen_counts = np.asarray(seen_cnt).astype(np.int64)
            tel.level(depth, frontier=lvl_frontier,
                      generated=generated - lvl_gen0,
                      new=int(np.asarray(tot_new)[0]), distinct=distinct,
                      seen=int(seen_counts.sum()), devices=D,
                      wall_s=round(time.time() - lvl_t0, 6))
            self._fp_occupancy = int(seen_counts.sum())
            if seen_counts.sum():
                self._shard_balance = float(
                    seen_counts.max() / (seen_counts.sum() / D))
            max_front = int(np.asarray(front_cnt).max(initial=0))
            # device->host frontier copies only when something needs
            # them (tracing, a violation to localize, or FC regrowth):
            # in the perf configuration (store_trace=False, clean level)
            # the frontier never leaves the device
            iw = np.asarray(inv_which)
            which = int(iw.min())
            need_host_rows = (self.store_trace or max_front > FC or
                              which != _BIG or graph is not None)
            front_rows_np = np.asarray(front_rows) if need_host_rows \
                else None
            if self.store_trace:
                # trim to the occupied prefix: keeping full G = D*A*FC
                # capacity per level would hold the padded expansion of
                # the whole search in host RAM
                keep = max(max_front, 1)
                self._levels.append(
                    (front_rows_np[:, :keep],
                     np.asarray(front_src)[:, :keep], expanding_FC))

            sids_per_dev = None
            if graph is not None:
                # behavior-graph bookkeeping: kept new rows register with
                # provenance a*(D*FCprev) + (d_src*FCprev + f) so
                # labels_flat and the flat parent-sid table resolve them;
                # then every explored candidate edge (revisits included)
                front_src_np = np.asarray(front_src)
                fcnt_np = np.asarray(front_cnt)
                Cprev = self.A * expanding_FC
                flat_rows, flat_prov, row_counts = [], [], []
                for d in range(D):
                    n = int(fcnt_np[d])
                    row_counts.append(n)
                    for i in range(n):
                        g = int(front_src_np[d, i])
                        d_src, cc = g // Cprev, g % Cprev
                        a, f = cc // expanding_FC, cc % expanding_FC
                        flat_rows.append(front_rows_np[d, i])
                        flat_prov.append(
                            a * (D * expanding_FC)
                            + d_src * expanding_FC + f)
                new_sids = graph.add_level(
                    np.asarray(flat_rows) if flat_rows
                    else np.zeros((0, self.PW), np.int32),
                    np.asarray(flat_prov, np.int64),
                    D * expanding_FC, fsids)
                if graph.collect_edges and ecand is not None:
                    eidx = np.nonzero(eexp)[0]
                    epar = np.empty(len(eidx), np.int64)
                    for k, c in enumerate(eidx):
                        g = int(esrc[c])
                        d_src, cc = g // Cprev, g % Cprev
                        epar[k] = d_src * expanding_FC + cc % expanding_FC
                    graph.add_edges(ecand[eidx], epar, fsids)
                sids_per_dev = []
                off = 0
                for d in range(D):
                    sids_per_dev.append(new_sids[off:off + row_counts[d]])
                    off += row_counts[d]

            if which != _BIG:
                nm = self.inv_fns[which][0]
                iv_dev = int(np.argmax(iw == which))
                iv_slot = int(np.asarray(inv_slot)[iv_dev])
                trace = self._mesh_trace_to(iv_dev, iv_slot, depth + 1)
                return self._mk(False, distinct, generated, depth + 1, t0,
                                warnings,
                                self._viol("invariant", nm, trace))
            depth += 1

            # next frontier: per-device kept rows; capacity grows to the
            # max shard (hash skew can route up to G rows to one device)
            fcount = front_cnt
            if max_front > FC:
                FC = _pow2_at_least(max_front, FC)
                k = min(front_rows_np.shape[1], FC)
                nf = np.full((D, FC, self.PW), SENTINEL, np.int32)
                nf[:, :k] = front_rows_np[:, :k]
                frontier = jnp.asarray(nf)
            else:
                frontier = front_rows[:, :FC]
            if graph is not None:
                # flat sid table for the NEXT level's frontier slots
                # (kept-row order is preserved by the compactions above)
                fsids = np.full(D * FC, -1, np.int64)
                for d in range(D):
                    for i, sid in enumerate(sids_per_dev[d]):
                        fsids[d * FC + i] = sid

            if self.max_states and distinct >= self.max_states:
                # a truncation point IS a level boundary: leave a
                # checkpoint so the run can be resumed past the limit
                if self.checkpoint_path:
                    self._mesh_ck(seen, seen_counts, frontier, fcount,
                                  FC, SC, depth, generated, distinct)
                self.log("-- state limit reached, search truncated")
                return self._mk(
                    True, distinct, generated, depth, t0, warnings,
                    truncated=True,
                    trunc_reason=f"max_states: distinct {distinct} >= "
                                 f"limit {self.max_states}")

            now = time.time()
            if now - last_progress >= self.progress_every:
                last_progress = now
                self.log(f"Progress({depth}): {generated} generated, "
                         f"{distinct} distinct, "
                         f"{int(np.asarray(tot_front)[0])} on queue.")
            if self.checkpoint_path and \
                    now - last_ck >= self.checkpoint_every:
                last_ck = now
                self._mesh_ck(seen, seen_counts, frontier, fcount, FC,
                              SC, depth, generated, distinct)
            lvl_frontier = int(np.sum(np.asarray(fcount)))

        if graph is not None:
            viol = self._check_live(graph, warnings)
            if viol is not None:
                return self._mk(False, distinct, generated, depth - 1,
                                t0, warnings, viol)
        self.log("Model checking completed. No error has been found.")
        self.log(f"{generated} states generated, {distinct} distinct "
                 f"states found, 0 states left on queue.")
        return self._mk(True, distinct, generated, depth - 1, t0, warnings)

    def _mk(self, ok, distinct, generated, diameter, t0, warnings,
            violation=None, truncated=False, drained=False,
            trunc_reason=None):
        tel = obs.current()
        self._por_finish(self._por_stats["ample"],
                         self._por_stats["expanded"],
                         self._por_stats["masked"], distinct)
        tel.high_water("device.mem_high_water_bytes",
                       obs.device_mem_high_water())
        occ = getattr(self, "_fp_occupancy", None)
        if occ is not None:
            tel.gauge("fingerprint.occupancy", occ)
        if self.exchange == "a2a":
            tel.gauge("mesh.a2a_gamma", round(self._a2a_gamma, 4))
            tel.gauge("mesh.a2a_spill", self._spill_rows)
            if self._max_bucket:
                tel.gauge("mesh.a2a_max_bucket", self._max_bucket)
        if self._shard_balance is not None:
            tel.gauge("mesh.shard_balance",
                      round(self._shard_balance, 4))
        if self._supersteps:
            # host_syncs counts SUPERSTEPS (one scalar-ring read per
            # dispatch); the gauge records the deepest fused dispatch
            tel.gauge("mesh.supersteps", self._supersteps)
            tel.gauge("mesh.superstep_levels",
                      self._superstep_levels_max)
        # ISSUE 12 result surface (mirrors bfs._mk_result): tier
        # summary, fingerprint collision bound, named truncations
        tiers_stats = None
        if self._tiers is not None and self._tiers.active:
            tiers_stats = self._tiers.stats()
            self._tiers.publish_gauges(occ or 0)
        n = float((occ or 0) + (len(self._tiers)
                                if self._tiers is not None else 0))
        collision_p = n * n * 2.0 ** -129
        tel.gauge("fingerprint.collision_p", collision_p)
        if truncated and trunc_reason is None:
            trunc_reason = "drain" if drained else "unattributed"
        if trunc_reason:
            tel.gauge("truncation.reason", trunc_reason)
        return CheckResult(ok=ok, distinct=distinct, generated=generated,
                           diameter=max(diameter, 0), violation=violation,
                           wall_s=time.time() - t0, truncated=truncated,
                           warnings=warnings, drained=drained,
                           trunc_reason=trunc_reason,
                           seen_mode="fingerprint",
                           collision_p=collision_p, tiers=tiers_stats)
