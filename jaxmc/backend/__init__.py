r"""Device-agnostic backend layer (ISSUE 11).

`jaxmc/tpu/` grew three engines (bfs/mesh/multihost) that were TPU-named
but already ran anywhere XLA does; no round since r01 has produced a
real device number because the engine layer was welded to that name and
to whatever platform jax initialized first.  This package makes
{tpu, gpu, cpu-XLA} first-class:

  BackendDescriptor   the value the engines are parameterized over —
                      platform, device count, mesh shape, the donation
                      policy (XLA:CPU ignores donation, accelerators
                      want it) and the capacity-profile NAMESPACE, so
                      caps learned on one platform can never warm-start
                      a different one (an 8-chip TPU's per-shard caps
                      are nonsense on a 1-device CPU run).
  describe_backend()  build the descriptor for the LIVE jax backend
                      (call after device init).
  oracle              the preflight oracle (jaxmc/backend/oracle.py):
                      probes every visible platform with a tiny
                      compile+dispatch in a timeout-guarded subprocess
                      (a dead accelerator tunnel must cost seconds,
                      not a hung run), picks the best live one, and
                      stamps the verdict + per-candidate probe walls
                      into telemetry (`backend.oracle_choice`).

The engines live in jaxmc/backend/{bfs,mesh,multihost}.py;
jaxmc/tpu/ remains as thin import shims for compatibility.  This
module itself never imports jax at import time — `python -m jaxmc.obs`
must keep working in an interp-only environment.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Tuple

#: platform preference for "best live backend": higher wins (matches
#: obs/report.py's demotion rank — a backend swap downward is a REGRESS)
PLATFORM_RANK = {"cpu": 1, "gpu": 2, "tpu": 3}

#: the selectable surface behind `--backend` (cli.py): "interp" and
#: "jax" keep their historical meaning; the platform names pin the jax
#: engine to one platform; "auto" asks the preflight oracle
BACKEND_CHOICES = ("interp", "jax", "auto", "cpu", "gpu", "tpu")


@dataclass(frozen=True)
class BackendDescriptor:
    """Everything an engine needs to know about the device layer it is
    compiled for.  One value, passed down instead of re-derived from
    global jax state in every engine, so bfs/mesh/multihost cannot
    disagree about the platform they are running on."""

    platform: str              # "cpu" | "gpu" | "tpu"
    device_count: int
    mesh_shape: Tuple[int, ...]  # (D,) — the 1-d "d" mesh axis
    donate: bool               # buffer-donation policy for jitted steps
    profile_ns: str            # capacity-profile namespace ("cpu", ...)

    def profile_variant(self, variant: str = "") -> str:
        """Namespace a capacity-profile variant by platform: caps
        learned on cpu-XLA must never warm a TPU run (and vice versa) —
        per-shard capacities, gamma and superstep budgets are all
        platform-shaped."""
        return f"{self.profile_ns}.{variant}" if variant \
            else self.profile_ns


def donation_default(platform: str) -> bool:
    """Donation policy: XLA:CPU ignores donation (with a warning), so
    it defaults on only for accelerator platforms; JAXMC_DONATE=1/0
    forces it either way (the ISSUE 6 rule, now a descriptor field)."""
    forced = os.environ.get("JAXMC_DONATE")
    if forced is not None:
        return forced == "1"
    return platform != "cpu"


def describe_backend(platform: Optional[str] = None,
                     device_count: Optional[int] = None
                     ) -> BackendDescriptor:
    """The descriptor for the LIVE jax backend (imports jax — call
    after device init).  `platform`/`device_count` override what jax
    reports (the mesh engines pass their actual mesh extent)."""
    import jax
    if platform is None:
        platform = jax.default_backend()
    if device_count is None:
        try:
            device_count = len(jax.devices())
        except RuntimeError:
            device_count = 1
    return BackendDescriptor(
        platform=platform, device_count=device_count,
        mesh_shape=(device_count,),
        donate=donation_default(platform),
        profile_ns=platform)
