r"""Multi-host (DCN) distributed BFS — SURVEY.md §2.3/§5 "distributed
communication backend".

The single-controller MeshExplorer shards over the devices of ONE
process. This module runs the SAME sharded level step (mesh.py
_get_mesh_step — compiled kernels, gather exchange by default — this
fixed-capacity loop cannot re-run a level on an a2a bucket overflow,
JAXMC_MESH_EXCHANGE overrides — fp128
hash-partitioned seen shards, psum'd totals) over a mesh that spans
SEVERAL jax processes, the way a TPU pod spans hosts: each process
contributes its local devices, `jax.distributed.initialize` wires the
coordinator, and the collectives ride the inter-process transport (Gloo
on CPU here; ICI/DCN on real pods — the program is identical, which is
the point of jax's multi-controller model).

Multi-controller discipline: every process executes the same host loop;
device data lives in global arrays built with
`jax.make_array_from_callback`; the host reads ONLY replicated psum'd
scalars (via its own addressable shard). The frontier keeps a FIXED
per-device capacity (the step's out_cap variant) so no process ever
needs another host's rows between levels; outgrowing it aborts loudly
with a replicated flag.

Validated end to end on this box by dryrun_multihost
(__graft_entry__.py): 2 processes x 4 virtual CPU devices run the FULL
reference-raft MCraftMicro model to completion with the pinned counts
(6185 generated / 694 distinct), exercising the same code path a
multi-host pod would (VERDICT r3 #7; ROADMAP gap 6).
"""

from __future__ import annotations

import os

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _local_scalar(arr) -> int:
    """Read a replicated (psum'd) per-device scalar from MY addressable
    shard — np.asarray(global_array) is illegal for non-addressable
    multi-process arrays."""
    import numpy as np
    return int(np.asarray(arr.addressable_shards[0].data).reshape(-1)[0])


def run_multihost_child(process_id: int, num_processes: int,
                        coordinator: str, local_devices: int = 4,
                        spec: str = None, cfg: str = None,
                        FC: int = 256, SC: int = 4096,
                        max_levels: int = 200,
                        store_trace: bool = True):
    """One process of the multi-host run. MUST be called before any other
    jax initialization in the process. Returns (generated, distinct,
    violation) — identical on every process (psum'd totals + the same
    gathered trace); violation is None for a clean run, else
    (kind, name, trace) with trace = [(state, action-label), ...], the
    exact counterexample the single-chip MeshExplorer produces for the
    same model over the same global device count (trace contract:
    /root/reference/README.md:268-318)."""
    import re
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags.strip() +
        f" --xla_force_host_platform_device_count={local_devices}")
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..sem.modules import Loader, bind_model
    from ..front.cfg import parse_cfg
    from .mesh import MeshExplorer

    devs = jax.devices()  # GLOBAL devices, across all processes
    D = len(devs)
    assert D == num_processes * local_devices, (D, num_processes)
    mesh = Mesh(np.array(devs), ("d",))

    spec = spec or os.path.join(_REPO, "specs", "MCraftMicro.tla")
    cfg = cfg or os.path.join(_REPO, "specs", "MCraft_micro.cfg")
    # the MC shims EXTEND specs that live in the reference checkout;
    # its location is machine-specific, so take it from the environment
    # rather than hardcoding this dev box's path
    ref_root = os.environ.get("JAXMC_REFERENCE_ROOT", "/root/reference")
    ref_examples = os.path.join(ref_root, "examples")
    search = [os.path.dirname(spec)]
    if os.path.isdir(ref_examples):
        search.append(ref_examples)
    model = bind_model(
        Loader(search).load_path(spec),
        parse_cfg(open(cfg).read()))

    # the compile pipeline is process-local and deterministic: both
    # processes build byte-identical kernels and step programs.
    # Exchange stays GATHER here even though a2a is the D>1 default
    # (ISSUE 8): this fixed-capacity multi-controller loop cannot
    # re-run a level, so an a2a bucket+spill overflow would abort a
    # run the gather exchange completes — JAXMC_MESH_EXCHANGE still
    # overrides for pods whose skew envelope is known.
    exchange = os.environ.get("JAXMC_MESH_EXCHANGE", "").strip() \
        or "gather"
    me = MeshExplorer(model, mesh=mesh, store_trace=False,
                      exchange=exchange)
    W, K = me.W, me.K

    # init states: identical host computation on every process (the
    # shard construction is shared with MeshExplorer.run — one layout
    # rule for host and device dedup)
    from .bfs import filter_init_states
    init_rows = np.stack([me.layout.encode(st) for st in me.init_states])
    explored, viol = filter_init_states(model, me.layout, init_rows)
    assert viol is None, "initial-state violation in the dryrun model"
    # per-shard seen occupancy (ISSUE 10): the step's merge now takes
    # the valid-prefix length explicitly (the rank strategy binary-
    # searches it; fullsort masks stale tail rows with it), so the
    # loop carries the step's seen-count output back into the next
    # level's input, seeded by the counts _init_shards built
    seen_h, front_h, fcount_h, scount_h = me._init_shards(
        init_rows, explored, D, SC, FC)

    def dist(h):
        sh = NamedSharding(mesh, P("d"))
        return jax.make_array_from_callback(
            h.shape, sh, lambda idx: h[idx])

    seen = dist(seen_h)
    seen_cnt = dist(scount_h)
    frontier, fcount = dist(front_h), dist(fcount_h)

    generated = len(init_rows)
    distinct = len(explored)
    step = me._get_mesh_step(SC, FC, out_cap=FC)
    depth = 0

    # ---- trace recording (VERDICT r4 #7): every process records ONLY
    # its own devices' frontier/provenance shards per level; on a
    # violation the full per-level arrays are reassembled with a
    # process_allgather PULL (the "gather protocol") and every process
    # independently walks the same provenance chain the single-chip
    # MeshExplorer walks (mesh.py _mesh_trace_to), producing the exact
    # same counterexample trace. Level 0 is the init frontier, which
    # every process computed identically on the host.
    from .bfs import SENTINEL

    def _partials(garr, fill, dtype):
        """(partial-full-array, ownership-mask) from MY addressable
        shards of a [D, ...]-sharded global array."""
        part = np.full(garr.shape, fill, dtype)
        mask = np.zeros(garr.shape[0], bool)
        for sh in garr.addressable_shards:
            part[sh.index] = np.asarray(sh.data)
            mask[sh.index[0]] = True
        return part, mask

    def _gather_full(part, mask):
        from jax.experimental import multihost_utils as mhu
        parts = np.asarray(mhu.process_allgather(part))
        masks = np.asarray(mhu.process_allgather(mask))
        out = part.copy()
        for pi in range(parts.shape[0]):
            out[masks[pi]] = parts[pi][masks[pi]]
        return out

    levels = [(front_h, None, np.ones(D, bool))] if store_trace else None

    def _assemble_trace(dev, slot, lvl, extra=None):
        full = []
        for rows_p, src_p, mask in levels[:lvl + 1]:
            if mask.all():
                full.append((rows_p, src_p))
            else:
                full.append((_gather_full(rows_p, mask),
                             _gather_full(src_p, mask)
                             if src_p is not None else None))
        out = []
        d, i = dev, slot
        C = me.A * FC
        for lv in range(lvl, -1, -1):
            rows, src = full[lv]
            st = me.layout.decode_packed(np.asarray(rows[d][i]))
            if lv == 0:
                out.append((st, "Initial predicate"))
            else:
                g = int(src[d][i])
                a = (g % C) // FC
                out.append((st, me.labels_flat[a]))
                d, i = g // C, (g % C) % FC
        out.reverse()
        if extra is not None:
            out.append(extra)
        return out

    def _first_bad_device(per_dev_partial, mask, pred):
        full = _gather_full(per_dev_partial, mask)
        for d in range(D):
            if pred(full[d]):
                return d, full
        return None, full

    while depth < max_levels:
        outs = step(seen, seen_cnt, frontier, fcount)
        (seen, seen_cnt, frontier, fcount, tot_gen, tot_new,
         any_ovf, tot_front, fixed_ovf, any_inv, any_dead,
         any_assert) = outs[:12]
        # index 20 is the psum'd a2a spill-row count (ISSUE 8): rows
        # drained by the second all_to_all pass instead of aborting
        (front_src, inv_which, inv_slot, dead_local, dead_slot,
         assert_bad, asrt_a, asrt_f) = outs[12:20]
        ovc = _local_scalar(any_ovf)  # 0 = none, else max kernel2.OV_*
        if ovc:
            from ..compile.kernel2 import OV_DEMOTED, OV_PACK
            if ovc == OV_DEMOTED:
                raise RuntimeError(
                    "a demoted compile-recovery fired in the multi-host "
                    "run (kernel under-approximates here): run the "
                    "host_seen mode — raising caps cannot help")
            if ovc == OV_PACK:
                raise RuntimeError(
                    "a value escaped its bit-packed lane's profiled "
                    "range in the multi-host run: deepen sampling or "
                    "rerun with JAXMC_PACK=0")
            raise RuntimeError("kernel capacity overflow in the "
                               "multi-host run")
        if _local_scalar(fixed_ovf):
            raise RuntimeError(
                f"fixed shard capacity exceeded (FC={FC}, SC={SC}): "
                f"raise them for this model")
        if store_trace:
            rows_p, mask = _partials(frontier, SENTINEL, np.int32)
            src_p, _ = _partials(front_src, -1, np.int32)
            levels.append((rows_p, src_p, mask))
        # violation precedence mirrors the single-chip MeshExplorer host
        # loop EXACTLY (mesh.py: deadlock -> assert -> invariant) so a
        # level with simultaneous violations yields the same verdict and
        # the same counterexample on both backends
        if model.check_deadlock and _local_scalar(any_dead):
            if store_trace:
                dl, mk = _partials(dead_local, 0, np.int32)
                ds = _partials(dead_slot, -1, np.int32)[0]
                d, _ = _first_bad_device(dl, mk, lambda x: x != 0)
                ds_f = _gather_full(ds, mk)
                tr = _assemble_trace(d, int(ds_f[d]), depth)
                return generated, distinct, ("deadlock", "deadlock", tr)
            raise RuntimeError("deadlock in the dryrun model")
        if _local_scalar(any_assert):
            # assert fires while EXPANDING the current frontier (level
            # `depth`): provenance is (action instance, frontier slot)
            if store_trace:
                ab, mk = _partials(assert_bad, 0, np.int32)
                am = _partials(asrt_a, -1, np.int32)[0]
                af = _partials(asrt_f, -1, np.int32)[0]
                d, ab_full = _first_bad_device(ab, mk, lambda x: x != 0)
                am_f = _gather_full(am, mk)
                af_f = _gather_full(af, mk)
                tr = _assemble_trace(d, int(af_f[d]), depth)
                nm = f"assertion in {me.labels_flat[int(am_f[d])]}"
                return generated, distinct, ("assert", nm, tr)
            raise RuntimeError("Assert violation in the dryrun model")
        if _local_scalar(any_inv):
            # invariant violations live in the NEW frontier (depth+1).
            # Selection mirrors mesh.py: the globally LOWEST violated
            # cfg-invariant index wins, then the first device holding it
            if store_trace:
                from .mesh import _BIG
                iw, mk = _partials(inv_which, int(_BIG), np.int32)
                isl = _partials(inv_slot, -1, np.int32)[0]
                iw_full = _gather_full(iw, mk)
                which = int(iw_full.min())
                d = int(np.argmax(iw_full == which))
                isl_f = _gather_full(isl, mk)
                nm = me.inv_fns[which][0]
                tr = _assemble_trace(d, int(isl_f[d]), depth + 1)
                return generated, distinct, ("invariant", nm, tr)
            raise RuntimeError("invariant violation in the dryrun model")
        generated += _local_scalar(tot_gen)
        distinct += _local_scalar(tot_new)
        depth += 1
        if _local_scalar(tot_front) == 0:
            return generated, distinct, None
    raise RuntimeError(f"did not converge in {max_levels} levels")


def fmt_trace_line(i, st, label) -> str:
    """One parseable line per trace step: deterministic state rendering
    (sorted vars, sem.values.fmt) so parent processes and tests compare
    multi-host traces against single-chip ones textually."""
    from ..sem.values import fmt
    body = " /\\ ".join(f"{v} = {fmt(st[v])}" for v in sorted(st))
    return f"MHTRACE {i}: [{label}] {body}"


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--num-processes", type=int, default=2)
    ap.add_argument("--coordinator", default="localhost:29521")
    ap.add_argument("--local-devices", type=int, default=4)
    ap.add_argument("--spec", default=None)
    ap.add_argument("--cfg", default=None)
    ap.add_argument("--fc", type=int, default=256)
    ap.add_argument("--sc", type=int, default=4096)
    a = ap.parse_args()
    gen, dist_, viol = run_multihost_child(
        a.process_id, a.num_processes, a.coordinator, a.local_devices,
        spec=a.spec, cfg=a.cfg, FC=a.fc, SC=a.sc)
    if viol is not None:
        kind, name, trace = viol
        print(f"MHVIOLATION p{a.process_id}: {kind} {name} "
              f"({len(trace)} states)", flush=True)
        for i, (st, label) in enumerate(trace):
            print(fmt_trace_line(i, st, label), flush=True)
    print(f"MULTIHOST p{a.process_id}: {gen} generated / "
          f"{dist_} distinct", flush=True)


if __name__ == "__main__":
    main()
