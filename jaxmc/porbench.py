r"""`make por-check` (ISSUE 15, device legs ISSUE 18): the
independence/reduction gate.

Five legs over the repo-local commuting fixtures (specs/portoy.tla,
specs/msgstoy.tla), one parseable `POR-CHECK …` line each:

  1. UNREDUCED   the exact serial run of portoy_ok; counts must equal
                 the corpus manifest pins.
  2. POR         the same rung under --por: the run must still
                 complete OK, report por.* gauges, and explore >= 30%
                 fewer distinct states than leg 1; the deadlock and
                 invariant rungs must keep their violation VERDICTS
                 under --por (trace-replay validity is pinned by
                 tests/test_independence.py).
  3. REGROUP     the jax host_seen grouped path at
                 JAXMC_FUSED_MAX_INSTANCES=2, independence regrouping
                 ON vs OFF: counts and the rendered counterexample
                 byte-identical; the regrouped artifact gates against
                 its saved baseline via `python -m jaxmc.obs diff
                 --fail-on-regress` (meshbench._gate, like every
                 bench-check leg).
  4. PREDICTED   a COLD resident run (fresh profile store) of a fully
                 proven spec must take the `predicted` capacity rung
                 and pay exactly ONE compile — zero growth-retry
                 recompiles (`window_recompiles == 0` in the serve
                 sense: no fresh compile after the first dispatch).
  5. DEVICE POR  `--por` on the jax backend runs the ample mask INSIDE
                 the fused device step (por.engine == "device" — no
                 interpreter demotion): the unreduced device run must
                 hit the manifest pins, the reduced run must cut
                 distinct states >= 30% on BOTH the static (portoy)
                 and dynamic-key (msgstoy) fixtures with the artifact
                 gated against its saved baseline, and the reduced
                 device run of the invariant rung must report the same
                 violation line as the unreduced device run.

A container without the jax backend prints `POR-CHECK SKIP …` for the
jax legs (3, 4, 5) and still runs the interpreter legs (1, 2) — the
POR filter itself is device-independent.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SPEC = "specs/portoy.tla"
_CFG_OK = "specs/portoy_ok.cfg"
_CFG_DEAD = "specs/portoy.cfg"
_CFG_BAD = "specs/portoy_bad.cfg"
#: acceptance floor: --por must cut explored distinct states by this
_MIN_REDUCTION = 0.30


def _check(cfg: str, metrics: Optional[str], extra: List[str],
           env_extra: Dict[str, str], timeout_s: float,
           spec: str = _SPEC) -> Dict:
    cmd = [sys.executable, "-m", "jaxmc", "check",
           os.path.join(_REPO, spec),
           "--cfg", os.path.join(_REPO, cfg), "--quiet"] + extra
    if metrics:
        cmd += ["--metrics-out", metrics]
    env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu",
               **env_extra)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, capture_output=True, text=True,
                           cwd=_REPO, env=env, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"error": f"leg timed out after {timeout_s:.0f}s"}
    out = {"rc": p.returncode, "stdout": p.stdout, "stderr": p.stderr,
           "wall_s": round(time.time() - t0, 3)}
    if metrics:
        try:
            with open(metrics, encoding="utf-8") as fh:
                out["summary"] = json.load(fh)
        except (OSError, ValueError) as ex:
            out["error"] = f"no metrics artifact ({ex})"
    return out


def _trace_lines(stdout: str) -> List[str]:
    lines = stdout.splitlines()
    for i, ln in enumerate(lines):
        if "is violated" in ln or "Error:" in ln:
            return lines[i:]
    return []


def _have_jax() -> bool:
    import importlib.util
    return importlib.util.find_spec("jax") is not None


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m jaxmc.porbench",
        description="independence/reduction gate (POR verdicts + "
                    "regroup parity + predicted capacity rung)")
    ap.add_argument("--out-dir", default="/tmp")
    ap.add_argument("--leg-timeout", type=float, default=float(
        os.environ.get("JAXMC_POR_CHECK_TIMEOUT", "600")))
    args = ap.parse_args(argv)

    from .corpus import case_for_cfg
    case = case_for_cfg(os.path.basename(_CFG_OK))
    want = (case.generated, case.distinct) if case else (366, 150)
    failures = 0

    # leg 1: unreduced exact baseline (serial: POR's comparison basis)
    m_base = os.path.join(args.out_dir, "jaxmc_por_unreduced.json")
    r = _check(_CFG_OK, m_base, ["--no-deadlock", "--workers", "1"],
               {}, args.leg_timeout)
    res = (r.get("summary") or {}).get("result") or {}
    if r.get("rc") != 0 or not res.get("ok") or \
            (res.get("generated"), res.get("distinct")) != want:
        print(f"POR-CHECK FAIL unreduced: rc={r.get('rc')} counts="
              f"{(res.get('generated'), res.get('distinct'))} != "
              f"manifest pins {want} "
              f"{(r.get('stderr') or '')[-200:]}", file=sys.stderr)
        return 1
    print(f"POR-CHECK ok unreduced: {want[0]} gen / {want[1]} "
          f"distinct ({r['wall_s']}s)")

    # leg 2: --por reduction + verdict preservation
    m_por = os.path.join(args.out_dir, "jaxmc_por_reduced.json")
    r2 = _check(_CFG_OK, m_por, ["--no-deadlock", "--por"], {},
                args.leg_timeout)
    res2 = (r2.get("summary") or {}).get("result") or {}
    gauges2 = (r2.get("summary") or {}).get("gauges") or {}
    red = 1.0 - (res2.get("distinct") or want[1]) / want[1]
    if r2.get("rc") != 0 or not res2.get("ok"):
        print(f"POR-CHECK FAIL por: rc={r2.get('rc')} "
              f"{(r2.get('stderr') or '')[-200:]}", file=sys.stderr)
        failures += 1
    elif red < _MIN_REDUCTION or not gauges2.get("por.enabled"):
        print(f"POR-CHECK FAIL por: explored-state reduction "
              f"{red:.0%} < {_MIN_REDUCTION:.0%} "
              f"(distinct {res2.get('distinct')} vs {want[1]}; "
              f"por.enabled={gauges2.get('por.enabled')})",
              file=sys.stderr)
        failures += 1
    else:
        print(f"POR-CHECK ok por: {res2.get('distinct')} distinct "
              f"(-{red:.0%}), ample_ratio="
              f"{gauges2.get('por.ample_ratio')} ({r2['wall_s']}s)")
    for cfg, wkind, wrc in ((_CFG_DEAD, "Deadlock", 1),
                            (_CFG_BAD, "Invariant NoFire", 1)):
        rv = _check(cfg, None, ["--por"], {}, args.leg_timeout)
        head = _trace_lines(rv.get("stdout", ""))[:1]
        if rv.get("rc") != wrc or not head or wkind not in head[0]:
            print(f"POR-CHECK FAIL por verdict: {cfg} rc="
                  f"{rv.get('rc')} head={head}", file=sys.stderr)
            failures += 1
        else:
            print(f"POR-CHECK ok por verdict: {cfg} -> {head[0]!r}")

    if not _have_jax():
        print("POR-CHECK SKIP regroup+predicted+device: jax backend "
              "unavailable in this container")
        print(f"por-check: {'FAIL' if failures else 'ok'} "
              f"({failures} failing legs)")
        return 1 if failures else 0

    from .meshbench import _gate as gate

    # leg 3: regroup parity on the grouped host_seen path (cap 2 forces
    # ceil(A/2) groups on the 4-arm fixture)
    genv = {"JAXMC_FUSED_MAX_INSTANCES": "2"}
    m_grp = os.path.join(args.out_dir, "jaxmc_por_regroup.json")
    ron = _check(_CFG_BAD, m_grp,
                 ["--backend", "jax", "--platform", "cpu",
                  "--host-seen"],
                 dict(genv, JAXMC_ANALYZE_INDEP="1"), args.leg_timeout)
    roff = _check(_CFG_BAD, None,
                  ["--backend", "jax", "--platform", "cpu",
                   "--host-seen"],
                  dict(genv, JAXMC_ANALYZE_INDEP="0"), args.leg_timeout)
    t_on, t_off = _trace_lines(ron.get("stdout", "")), \
        _trace_lines(roff.get("stdout", ""))
    if ron.get("rc") != 1 or roff.get("rc") != 1 or not t_on or \
            t_on != t_off:
        print(f"POR-CHECK FAIL regroup: grouped runs differ with "
              f"regrouping on/off (rc {ron.get('rc')}/"
              f"{roff.get('rc')}, {len(t_on)} vs {len(t_off)} trace "
              f"lines) {(ron.get('stderr') or '')[-200:]}",
              file=sys.stderr)
        failures += 1
    else:
        print(f"POR-CHECK ok regroup: counterexample byte-identical "
              f"with regrouping on/off ({len(t_on)} lines)")
        if gate(m_grp, log=print,
                ignore_phases=("device_init", "engine_build",
                               "layout_sample", "compile_arm")):
            failures += 1

    # leg 4: predicted capacity rung — cold resident run, fresh store
    with tempfile.TemporaryDirectory(prefix="jaxmc_pred_") as store:
        m_pred = os.path.join(args.out_dir, "jaxmc_por_predicted.json")
        rp = _check(_CFG_OK, m_pred,
                    ["--no-deadlock", "--backend", "jax",
                     "--platform", "cpu", "--resident", "--no-trace"],
                    {"JAXMC_PROFILE_STORE": store}, args.leg_timeout)
        resp = (rp.get("summary") or {}).get("result") or {}
        gp = (rp.get("summary") or {}).get("gauges") or {}
        levels = (rp.get("summary") or {}).get("levels") or []
        fresh = sum(1 for lv in levels if lv.get("fresh_compile"))
        window = sum(1 for lv in levels[1:] if lv.get("fresh_compile"))
        if rp.get("rc") != 0 or not resp.get("ok") or \
                (resp.get("generated"), resp.get("distinct")) != want:
            print(f"POR-CHECK FAIL predicted: rc={rp.get('rc')} "
                  f"counts={(resp.get('generated'), resp.get('distinct'))}"
                  f" != {want} {(rp.get('stderr') or '')[-200:]}",
                  file=sys.stderr)
            failures += 1
        elif gp.get("profile.predicted_states") is None or window:
            print(f"POR-CHECK FAIL predicted: cold run must take the "
                  f"predicted rung with zero growth recompiles "
                  f"(predicted_states="
                  f"{gp.get('profile.predicted_states')}, "
                  f"fresh_compiles={fresh}, in-window={window})",
                  file=sys.stderr)
            failures += 1
        else:
            print(f"POR-CHECK ok predicted: cold resident run, "
                  f"predicted<={gp['profile.predicted_states']} "
                  f"states, {fresh} compile, 0 growth recompiles "
                  f"({rp['wall_s']}s)")

    # leg 5: DEVICE POR (ISSUE 18) — the ample mask runs INSIDE the
    # fused device step (por.engine == "device", no interpreter
    # demotion): >= 30% fewer distinct states than the unreduced
    # device run on both the static (portoy) and dynamic-key
    # (msgstoy) fixtures, artifact gated against its saved baseline
    dev = ["--no-deadlock", "--backend", "jax", "--platform", "cpu",
           "--host-seen"]
    mcase = case_for_cfg("msgstoy.cfg")
    mwant = (mcase.generated, mcase.distinct) if mcase else (1108, 324)
    for spec, cfg, pins, tag in (
            (_SPEC, _CFG_OK, want, "portoy"),
            ("specs/msgstoy.tla", "specs/msgstoy.cfg", mwant,
             "msgstoy")):
        m_unr = os.path.join(args.out_dir,
                             f"jaxmc_por_device_{tag}_unreduced.json")
        ru = _check(cfg, m_unr, dev, {}, args.leg_timeout, spec=spec)
        resu = (ru.get("summary") or {}).get("result") or {}
        m_dev = os.path.join(args.out_dir,
                             f"jaxmc_por_device_{tag}.json")
        rd = _check(cfg, m_dev, dev + ["--por"], {}, args.leg_timeout,
                    spec=spec)
        resd = (rd.get("summary") or {}).get("result") or {}
        gd = (rd.get("summary") or {}).get("gauges") or {}
        red = 1.0 - (resd.get("distinct") or pins[1]) / pins[1]
        if ru.get("rc") != 0 or \
                (resu.get("generated"), resu.get("distinct")) != pins:
            print(f"POR-CHECK FAIL device {tag}: unreduced device "
                  f"counts {(resu.get('generated'), resu.get('distinct'))}"
                  f" != manifest pins {pins} "
                  f"{(ru.get('stderr') or '')[-200:]}", file=sys.stderr)
            failures += 1
        elif rd.get("rc") != 0 or not resd.get("ok") or \
                gd.get("por.engine") != "device" or \
                not gd.get("por.enabled"):
            print(f"POR-CHECK FAIL device {tag}: rc={rd.get('rc')} "
                  f"por.engine={gd.get('por.engine')!r} "
                  f"por.enabled={gd.get('por.enabled')} "
                  f"{(rd.get('stderr') or '')[-200:]}", file=sys.stderr)
            failures += 1
        elif red < _MIN_REDUCTION:
            print(f"POR-CHECK FAIL device {tag}: reduction {red:.0%} "
                  f"< {_MIN_REDUCTION:.0%} (distinct "
                  f"{resd.get('distinct')} vs {pins[1]})",
                  file=sys.stderr)
            failures += 1
        else:
            print(f"POR-CHECK ok device {tag}: "
                  f"{resd.get('distinct')} distinct (-{red:.0%}), "
                  f"masked_arms={gd.get('por.device_masked_arms')}, "
                  f"ample_ratio={gd.get('por.ample_ratio')} "
                  f"({rd['wall_s']}s)")
            if gate(m_dev, log=print,
                    ignore_phases=("device_init", "engine_build",
                                   "layout_sample", "compile_arm")):
                failures += 1

    # device verdict: the reduced device run must report the SAME
    # violation line as the unreduced device run (trace-replay
    # validity is pinned by tests/test_independence.py)
    dbad = ["--backend", "jax", "--platform", "cpu", "--host-seen"]
    vu = _check(_CFG_BAD, None, dbad, {}, args.leg_timeout)
    vd = _check(_CFG_BAD, None, dbad + ["--por"], {}, args.leg_timeout)
    h_u = _trace_lines(vu.get("stdout", ""))[:1]
    h_d = _trace_lines(vd.get("stdout", ""))[:1]
    if vu.get("rc") != 1 or vd.get("rc") != 1 or not h_u or \
            h_u != h_d:
        print(f"POR-CHECK FAIL device verdict: rc {vu.get('rc')}/"
              f"{vd.get('rc')} heads {h_u} vs {h_d} "
              f"{(vd.get('stderr') or '')[-200:]}", file=sys.stderr)
        failures += 1
    else:
        print(f"POR-CHECK ok device verdict: {_CFG_BAD} -> "
              f"{h_d[0]!r} (matches unreduced device run)")

    # land the por-check leg artifacts in the persistent run ledger
    # (ISSUE 18): the unreduced-vs-reduced trajectory per fixture —
    # idempotent by content id, never breaks the gate
    try:
        from .obs import ledger as _ledger
        arts = [os.path.join(args.out_dir, f) for f in (
            "jaxmc_por_unreduced.json", "jaxmc_por_reduced.json",
            "jaxmc_por_device_portoy_unreduced.json",
            "jaxmc_por_device_portoy.json",
            "jaxmc_por_device_msgstoy_unreduced.json",
            "jaxmc_por_device_msgstoy.json")]
        _ledger.import_artifacts([a for a in arts
                                  if os.path.exists(a)])
    except Exception:  # noqa: BLE001 — the ledger never breaks a gate
        pass

    print(f"por-check: {'FAIL' if failures else 'ok'} "
          f"({failures} failing legs)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
