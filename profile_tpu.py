"""One-off profiler: where does the host_seen chunk loop spend time on TPU?"""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import numpy as np
import jax, jax.numpy as jnp

from jaxmc.sem.modules import Loader, bind_model
from jaxmc.front.cfg import parse_cfg
from jaxmc.backend.bfs import TpuExplorer, SENTINEL
from jaxmc import native_store

_REPO = os.path.dirname(os.path.abspath(__file__))
SPEC = os.path.join(_REPO, "specs", "MCraftMicro.tla")
CFG = os.path.join(_REPO, "specs", "MCraft_3s_bench.cfg")

def load_model():
    ldr = Loader([os.path.join(_REPO, "specs"), "/root/reference/examples"])
    return bind_model(ldr.load_path(SPEC), parse_cfg(open(CFG).read()))

print("platform:", jax.devices()[0].platform)

# tunnel roundtrip latency
x = jnp.ones((8,), jnp.int32)
x.block_until_ready()
t0 = time.time()
for _ in range(10):
    np.asarray(x + 1)
print(f"scalar roundtrip: {(time.time()-t0)/10*1000:.1f} ms")

big = jnp.ones((614000, 5), jnp.int32)
big.block_until_ready()
t0 = time.time()
np.asarray(big)
print(f"12MB transfer: {(time.time()-t0)*1000:.1f} ms")

ex = TpuExplorer(load_model(), store_trace=False, host_seen=True)
print(f"A={ex.A} W={ex.W} chunk={ex.chunk} K={ex.K}")

CH = 2048
hstep = ex._get_hstep(CH)

# build a frontier from init + run a few levels manually with timers
rows = {}
for st in ex.init_states:
    rows[ex.layout.encode(st).tobytes()] = st
init_rows = np.stack([np.frombuffer(kk, dtype=np.int32) for kk in rows])
frontier_np = init_rows
store = native_store.FingerprintStore()

tot = dict(dispatch=0.0, consume=0.0, insert=0.0, gather=0.0)
t_all = time.time()
for depth in range(8):
    L = len(frontier_np)
    new_rows_all = []
    nchunks = 0
    for base in range(0, L, CH):
        nchunks += 1
        cn = min(CH, L - base)
        buf = np.full((CH, ex.W), SENTINEL, np.int32)
        buf[:cn] = frontier_np[base:base + cn]
        t0 = time.time()
        out = hstep(jnp.asarray(buf), cn)
        jax.block_until_ready(out)
        t1 = time.time()
        cvalid = np.asarray(out["cvalid"])
        keys = np.asarray(out["keys"])
        explore = np.asarray(out["explore"])
        t2 = time.time()
        valid_idx = np.nonzero(cvalid)[0]
        new_mask = store.insert(keys[valid_idx][:, 1:])
        new_idx = valid_idx[new_mask]
        t3 = time.time()
        if len(new_idx):
            rows_np = np.asarray(jnp.take(out["cand"],
                                          jnp.asarray(new_idx, dtype=np.int32),
                                          axis=0))
            new_rows_all.append(rows_np[explore[new_idx]])
        t4 = time.time()
        tot["dispatch"] += t1 - t0
        tot["consume"] += t2 - t1
        tot["insert"] += t3 - t2
        tot["gather"] += t4 - t3
    frontier_np = (np.concatenate(new_rows_all) if new_rows_all
                   else np.zeros((0, ex.W), np.int32))
    print(f"level {depth}: frontier {L} -> {len(frontier_np)}  "
          f"chunks={nchunks}  {dict((k, round(v,2)) for k,v in tot.items())}")
print(f"total {time.time()-t_all:.1f}s  {tot}")
