------------------------- MODULE pcal_intro_buggy -------------------------
\* The README's race-condition variant of the money transfer: the balance
\* check (Transfer), the debit (A), and the credit (B) are separate atomic
\* steps, so two processes can interleave and drive alice_account negative.
\* Reference behavior: TLC stops at the assertion violation after
\* "9097 states generated, 6164 distinct states found" at search depth 7
\* (/root/reference/README.md:265-321). This spec is jaxmc's regression
\* fixture for that oracle run (algorithm from README.md:222-241).
EXTENDS Naturals, TLC

(* --algorithm transfer
variables alice_account = 10, bob_account = 10,
          account_total = alice_account + bob_account

process TransProc \in 1..2
  variables money \in 1..20;
begin
  Transfer:
    if alice_account >= money then
      A: alice_account := alice_account - money;
      B: bob_account := bob_account + money;
    end if;
C: assert alice_account >= 0;
end process

end algorithm *)

MoneyInvariant == alice_account + bob_account = account_total
=============================================================================
