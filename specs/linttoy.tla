---------------------------- MODULE linttoy ----------------------------
(* Deliberately UNCLEAN fixture for `python -m jaxmc.analyze lint`
   (ISSUE 9): every diagnostic class fires exactly where the comments
   say.  The model is lint-only — the cfg names an undefined invariant
   and `ghost` is never assigned, so it is not checkable and the corpus
   manifest carries it as a lint_only case (no search runs it).

     JMC101  cfg INVARIANT names `Missing` (undefined below)
     JMC102  CONSTANT Ghost is declared but the cfg never assigns it
     JMC201  VARIABLE ghost is never referenced
     JMC202  Stuck's guard x > Limit + 99 is statically false:
             the analyzer proves x \in [0, Limit]
     JMC203  Lowest CHOOSEs over the symmetry set P (order-sensitive)
     JMC301  Orphan is defined but unreachable from the cfg
     JMC302  CONSTANT Unused is assigned but never referenced       *)
EXTENDS Naturals, FiniteSets, TLC

CONSTANTS P, Limit, Unused, Ghost
VARIABLES x, ghost

Perms == Permutations(P)

Init == x = 0

Bump == x < Limit /\ x' = x + 1

Stuck == x > Limit + 99 /\ x' = x

Next == Bump \/ Stuck

Spec == Init /\ [][Next]_x

Orphan == x + 1

Lowest == CHOOSE p \in P : TRUE

HazInv == Lowest \in P

TypeInv == x \in 0..Limit
=========================================================================
