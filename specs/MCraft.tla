------------------------------- MODULE MCraft -------------------------------
\* Model-checking shim for the reference raft spec
\* (/root/reference/examples/raft.tla), following the corpus MC-module idiom
\* (MCPaxos.tla etc., SURVEY.md §5 "config system"). raft ships no .cfg;
\* BASELINE.json pins the benchmark model: Server={s1,s2,s3}, bounded log.
\* Terms and log lengths are bounded by a CONSTRAINT exactly as TLC users do
\* for raft (the spec's state space is otherwise infinite via Timeout).
EXTENDS raft

CONSTANTS MaxTerm, MaxLogLen

StateConstraint ==
    /\ \A i \in Server : currentTerm[i] <= MaxTerm
    /\ \A i \in Server : Len(log[i]) <= MaxLogLen

\* The safety properties raft.tla:500-507 tracks
NoMoreThanOneLeader == ~MoreThanOneLeader

NoLogDecrease == committedLogDecrease = FALSE
=============================================================================
