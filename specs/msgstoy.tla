---------------------------- MODULE msgstoy ----------------------------
(* Raft-shaped dynamic-key fixture (ISSUE 18): `msgs` is a per-process
   message table and every Send arm writes exactly ONE element,
   msgs[self], so the Send arms commute at the element-atom level —
   the independence analysis must classify them element-commuting and
   --por must reduce the search without touching the verdicts.  Tick
   exercises the DYNAMIC \E shape: a state-dependent filter over a
   static base set stays one arm (the splitter cannot instantiate it),
   whose binder key resolves to the base-set domain as a key SET.
   Flush reads one CONSTANT-keyed element, so exactly Send(P1)
   conflicts with it and every other Send stays por-safe. *)
EXTENDS Naturals
CONSTANTS Procs, Cap, T, P1
VARIABLES msgs, clock, done

Init == /\ msgs = [p \in Procs |-> 0]
        /\ clock = [n \in 1..T |-> 0]
        /\ done = FALSE

Send(p) == /\ msgs[p] < Cap
           /\ msgs' = [msgs EXCEPT ![p] = @ + 1]
           /\ UNCHANGED <<clock, done>>

Tick == /\ \E n \in {m \in 1..T : clock[m] < Cap} :
               clock' = [clock EXCEPT ![n] = @ + 1]
        /\ UNCHANGED <<msgs, done>>

Flush == /\ msgs[P1] = Cap
         /\ ~done
         /\ done' = TRUE
         /\ UNCHANGED <<msgs, clock>>

Next == (\E p \in Procs : Send(p)) \/ Tick \/ Flush

DoneOK == done \in BOOLEAN
=======================================================================
