----------------------------- MODULE MCserializableSI -----------------------
\* Model-checking shim for Cahill's serializable-snapshot-isolation spec
\* (/root/reference/examples/serializableSnapshotIsolation.tla), encoding
\* the spec's documented Toolbox model (:43-96). Unlike textbook SI, here
\* BOTH serializability formulations must HOLD (:75-79) — SSI is the
\* algorithm PostgreSQL ships.
EXTENDS serializableSnapshotIsolation

MCWellFormed == WellFormedTransactionsInHistory(history)

MCCahillSerializable == CahillSerializable(history)

MCBernsteinSerializable == BernsteinSerializable(history)
=============================================================================
