----------------------------- MODULE MCserializableSI -----------------------
\* Model-checking shim for Cahill's serializable-snapshot-isolation spec
\* (/root/reference/examples/serializableSnapshotIsolation.tla), encoding
\* the spec's documented Toolbox model (:43-96). Unlike textbook SI, here
\* BOTH serializability formulations must HOLD (:75-79) — SSI is the
\* algorithm PostgreSQL ships.
EXTENDS serializableSnapshotIsolation

MCWellFormed == WellFormedTransactionsInHistory(history)

MCCahillSerializable == CahillSerializable(history)

MCBernsteinSerializable == BernsteinSerializable(history)

\* Prune ChooseToAbort's branching (an abort at every state): algorithmic
\* aborts (FCW, deadlock-prevention, the three "to preserve
\* serializability" reasons) stay reachable — they ARE the algorithm
MCNoVoluntaryAborts ==
    \A i \in 1..Len(history) :
        history[i].op = "abort" => history[i].reason /= "voluntary"

\* Seeded initial state following MCtextbookSI's MCInitSeeded idiom: one
\* transaction has already committed writes to two keys, so every later
\* txn can read both keys from the start — the write-skew dangerous
\* structure then needs only the two remaining transactions. Cahill flags
\* and SIREAD locks start clear, exactly what Begin..Commit of the seed
\* txn produces (internalAbort/Commit reset them,
\* serializableSnapshotIsolation.tla:406-416).
MCSeedTxn == CHOOSE t \in TxnId : TRUE
MCk1 == CHOOSE k \in Key : TRUE
MCk2 == CHOOSE k \in Key \ {MCk1} : TRUE
MCInitSeeded ==
    /\ history = << [op |-> "begin",  txnid |-> MCSeedTxn],
                    [op |-> "write",  txnid |-> MCSeedTxn, key |-> MCk1],
                    [op |-> "write",  txnid |-> MCSeedTxn, key |-> MCk2],
                    [op |-> "commit", txnid |-> MCSeedTxn] >>
    /\ holdingXLocks      = [txn \in TxnId |-> {}]
    /\ waitingForXLock    = [txn \in TxnId |-> NoLock]
    /\ inConflict         = [txn \in TxnId |-> FALSE]
    /\ outConflict        = [txn \in TxnId |-> FALSE]
    /\ holdingSIREADlocks = [txn \in TxnId |-> {}]

\* Tighter seed for the fast end-to-end mutation pin: additionally seed
\* the second transaction's begin, its read of MCk1 (with the SIREAD
\* lock that read acquires) and its write of MCk2 (with the xlock) —
\* conflict flags still all FALSE, exactly what those operations produce
\* from MCInitSeeded. The write-skew dangerous structure then needs only
\* ~5 more events. NOT used for the read-family mutations: their
\* violations need the second transaction's READ to happen after the
\* mutation is live (a seeded SIREAD lock would mask e.g.
\* read_no_siread_lock).
MCTxn2 == CHOOSE t \in TxnId \ {MCSeedTxn} : TRUE
MCInitSeeded2 ==
    /\ history = << [op |-> "begin",  txnid |-> MCSeedTxn],
                    [op |-> "write",  txnid |-> MCSeedTxn, key |-> MCk1],
                    [op |-> "write",  txnid |-> MCSeedTxn, key |-> MCk2],
                    [op |-> "commit", txnid |-> MCSeedTxn],
                    [op |-> "begin",  txnid |-> MCTxn2],
                    [op |-> "read",   txnid |-> MCTxn2, key |-> MCk1,
                     ver |-> MCSeedTxn],
                    [op |-> "write",  txnid |-> MCTxn2, key |-> MCk2] >>
    /\ holdingXLocks      = [txn \in TxnId |->
                                IF txn = MCTxn2 THEN {MCk2} ELSE {}]
    /\ waitingForXLock    = [txn \in TxnId |-> NoLock]
    /\ inConflict         = [txn \in TxnId |-> FALSE]
    /\ outConflict        = [txn \in TxnId |-> FALSE]
    /\ holdingSIREADlocks = [txn \in TxnId |->
                                IF txn = MCTxn2 THEN {MCk1} ELSE {}]

\* 3-key escalation seed (write-family mutations): with 2 keys the
\* read- and commit-checks alone still block every dangerous cycle a
\* single write-mutation opens (the late-out hole needs a wr edge
\* through a THIRD key to close a cycle whose last committer carries at
\* most one flag). Seed txn commits all three keys.
MCk3 == CHOOSE k \in Key \ {MCk1, MCk2} : TRUE
MCInitSeeded3K ==
    /\ history = << [op |-> "begin",  txnid |-> MCSeedTxn],
                    [op |-> "write",  txnid |-> MCSeedTxn, key |-> MCk1],
                    [op |-> "write",  txnid |-> MCSeedTxn, key |-> MCk2],
                    [op |-> "write",  txnid |-> MCSeedTxn, key |-> MCk3],
                    [op |-> "commit", txnid |-> MCSeedTxn] >>
    /\ holdingXLocks      = [txn \in TxnId |-> {}]
    /\ waitingForXLock    = [txn \in TxnId |-> NoLock]
    /\ inConflict         = [txn \in TxnId |-> FALSE]
    /\ outConflict        = [txn \in TxnId |-> FALSE]
    /\ holdingSIREADlocks = [txn \in TxnId |-> {}]

\* Serializability can only NEWLY fail at a commit: both MVSG encodings
\* build their graphs from COMMITTED transactions, so a history is
\* non-serializable iff its prefix ending at the latest commit is. These
\* guarded forms skip the O(|Txn|^2 |Key|) graph construction on every
\* non-commit state — same violations, found at the same states.
MCCahillSerializableAtCommit ==
    \/ Len(history) = 0
    \/ history[Len(history)].op /= "commit"
    \/ CahillSerializable(history)

MCBernsteinSerializableAtCommit ==
    \/ Len(history) = 0
    \/ history[Len(history)].op /= "commit"
    \/ BernsteinSerializable(history)

\* "Interesting history" finders (spec header :94-96): EXPECTED to be
\* violated — the search must reach a state where SSI actually fired a
\* serializability abort, proving the dangerous-structure machinery is
\* exercised (not vacuously passed) at this model size
MCNoWriteSerializabilityAbort ==
    ~ AtLeastNTxnsAbortedDueToReason(
          1, "in attempted write, to preserve serializability")
MCNoReadSerializabilityAbort ==
    ~ AtLeastNTxnsAbortedDueToReason(
          1, "in attempted read, to preserve serializability")
=============================================================================
