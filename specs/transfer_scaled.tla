------------------------- MODULE transfer_scaled -------------------------
\* Scalable benchmark workload for jaxmc: the README money-transfer race
\* (/root/reference/README.md:222-241) generalized to N processes and a
\* configurable money domain, written directly in TLA+ so the state-space
\* size is cfg-tunable. Safety: alice only ever decreases (AliceBounded),
\* which holds despite the race. This is the round-1 flagship bench spec
\* (raft.tla is the round-2+ target, SURVEY.md §6).
EXTENDS Naturals

CONSTANTS Procs, MaxMoney

VARIABLES alice, bob, money, pc

vars == <<alice, bob, money, pc>>

Init == /\ alice = MaxMoney
        /\ bob = 0
        /\ money \in [Procs -> 1..MaxMoney]
        /\ pc = [p \in Procs |-> "check"]

Check(p) == /\ pc[p] = "check"
            /\ pc' = [pc EXCEPT ![p] =
                         IF alice >= money[p] THEN "debit" ELSE "done"]
            /\ UNCHANGED <<alice, bob, money>>

Debit(p) == /\ pc[p] = "debit"
            /\ alice' = alice - money[p]
            /\ pc' = [pc EXCEPT ![p] = "credit"]
            /\ UNCHANGED <<bob, money>>

Credit(p) == /\ pc[p] = "credit"
             /\ bob' = bob + money[p]
             /\ pc' = [pc EXCEPT ![p] = "done"]
             /\ UNCHANGED <<alice, money>>

Terminating == /\ \A p \in Procs : pc[p] = "done"
               /\ UNCHANGED vars

Next == (\E p \in Procs : Check(p) \/ Debit(p) \/ Credit(p)) \/ Terminating

Spec == Init /\ [][Next]_vars

AliceBounded == alice <= MaxMoney
=============================================================================
