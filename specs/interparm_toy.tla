------------------------- MODULE interparm_toy -------------------------
(* Mode-pin enforcement fixture (ISSUE 5): Pick's FIRST item assigns
   `Cardinality(SUBSET s)` with no guard before it — SUBSET of a
   symbolic (state-dependent) set is outside the kernel compiler's
   subset and, with the action statically enabled, there is no
   guard-demotion recovery to hide behind: the arm demotes AT BUILD
   TIME and the model is hybrid BY CONSTRUCTION.  The repo-local
   representative of the demoted-arm class, used to pin the sweep's
   mode-slide failure path and the per-arm demotion reason table
   without needing the reference tree. *)
EXTENDS Naturals, FiniteSets
VARIABLES x, s

Init == x = 0 /\ s = {}
Bump == x < 4 /\ x' = x + 1 /\ s' = s \cup {x}
Pick == x' = Cardinality(SUBSET s) /\ s' = s
Next == Bump \/ Pick
Spec == Init /\ [][Next]_<<x, s>>
TypeInv == x \in 0..16 /\ s \subseteq 0..3
=========================================================================
