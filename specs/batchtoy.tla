---------------------------- MODULE batchtoy ----------------------------
(* The cross-model batching fixture family (ISSUE 13).  One module,
   several cfgs that differ ONLY in constant values every use of which
   is a pure VALUE position (guards, arithmetic, invariant/constraint
   comparisons) — so analyze/bounds.liftable_constants proves all four
   liftable and every cfg in the family is layout-compatible by
   construction: the serve fleet checks them through ONE vmapped
   device program.  batchtoy_bad picks Bound below the reachable x
   maximum, so a mixed batch exercises one member violating while the
   others run to exhaustion. *)
EXTENDS Naturals

CONSTANTS Limit, Step, Bound, WrapCap

VARIABLES x, wraps

Init == x = 0 /\ wraps = 0

Tick == x < Limit /\ x' = x + Step /\ wraps' = wraps

Wrap == x >= Limit /\ x' = 0 /\ wraps' = wraps + 1

Next == Tick \/ Wrap

Spec == Init /\ [][Next]_<<x, wraps>>

InBound == x =< Bound

StateCap == wraps =< WrapCap
=========================================================================
