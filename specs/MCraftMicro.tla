---------------------------- MODULE MCraftMicro ----------------------------
\* A raft model small enough to run to COMPLETION on every backend — the
\* whole-run count-equality fixture (BASELINE.json "identical reachable-state
\* count"). Extends the MCraft shim (itself extending the reference raft,
\* /root/reference/examples/raft.tla) with a bound on the message-bag domain:
\* raft's WithMessage (raft.tla:117-121) grows DOMAIN messages without bound
\* even at MaxTerm=2/MaxLogLen=1, which is why MCraft_tiny never finishes.
\* Bounding the domain cardinality is the standard TLC trick for making the
\* bag finite (same idiom as qConstraint, MCInnerFIFO.cfg).
EXTENDS MCraft, FiniteSets

CONSTANT MaxMsgDomain

MsgConstraint == Cardinality(DOMAIN messages) <= MaxMsgDomain
=============================================================================
