---------------------------- MODULE symtoy_scaled ----------------------------
(* The symtoy SYMMETRY fixture at BENCH scale (ISSUE 6): same shape —
   processes grab a token, `owner`/`used`/`turns` exercise the enum,
   set-membership and function-block canonicalization transforms — with
   a cfg-tunable process count and turn bound so the symmetry-reduced
   space is thousands of states.  The kernel-vs-interp bench leg runs
   this rung; the tiny symtoy stays the parity fixture. *)
EXTENDS Naturals, FiniteSets, TLC

CONSTANTS P, None, MaxTurns, K

VARIABLES owner, used, turns

Perms == Permutations(P)

Init == owner = None /\ used = {} /\ turns = [p \in P |-> 0]

Grab(p, k) == /\ turns[p] + k =< MaxTurns
              /\ owner' = p
              /\ used' = used \cup {p}
              /\ turns' = [turns EXCEPT ![p] = @ + k]

Release == /\ owner /= None
           /\ owner' = None
           /\ UNCHANGED <<used, turns>>

Next == \/ owner = None /\ \E p \in P, k \in 1..K : Grab(p, k)
        \/ Release

Spec == Init /\ [][Next]_<<owner, used, turns>>

TypeInv == /\ owner \in P \cup {None}
           /\ used \subseteq P
           /\ \A p \in P : turns[p] \in 0..MaxTurns
=============================================================================
