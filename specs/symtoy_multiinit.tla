------------------------ MODULE symtoy_multiinit ------------------------
(* symtoy with a nondeterministic Init whose states share a symmetry
   orbit: `owner \in P` gives |P| raw initial states that collapse to
   ONE canonical representative under SYMMETRY Permutations(P). Pins the
   device backends' init-state canonicalization (advisor r2 high:
   _prepare_init must dedup by canonical keys, not raw encodings). *)
EXTENDS Naturals, FiniteSets, TLC
CONSTANTS P, None
VARIABLES owner, used, turns

Perms == Permutations(P)

Init == owner \in P /\ used = {} /\ turns = [p \in P |-> 0]

Grab(p) == /\ owner' = p
           /\ used' = used \cup {p}
           /\ turns' = [turns EXCEPT ![p] = @ + 1]

Release == /\ owner /= None
           /\ owner' = None
           /\ UNCHANGED <<used, turns>>

Next == \/ owner = None /\ \E p \in P : turns[p] < 2 /\ Grab(p)
        \/ Release

Spec == Init /\ [][Next]_<<owner, used, turns>>

TypeInv == /\ owner \in P \cup {None}
           /\ used \subseteq P
           /\ turns \in [P -> 0..2]
=========================================================================
