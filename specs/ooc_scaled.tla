---------------------------- MODULE ooc_scaled ----------------------------
(* Out-of-core overflow fixture (ISSUE 12): a WIDE-state rung sized so a
   tiny forced device seen cap (JAXMC_SEEN_CAP / --seen-cap) drives the
   hierarchical seen set through BOTH the host-RAM and disk tiers in
   seconds, with counts/traces pinned bit-identical against the
   uncapped run.

   Shape: a (clock, x) product chain gives C * M = 3072 distinct states
   over a shallow-but-wide BFS, while `mem` — N cells whose values
   churn over 0..Span-1 as a pure function of clock — makes the PACKED
   row deliberately wide: ~37 packed words at N = 18, wide enough that
   exact dedup keys cost >7x a 128-bit fingerprint (the measurable
   4-8x states-per-tier trade the ooc-check leg and BASELINE.md
   record) yet still under FP_THRESHOLD, so exact keys stay the auto
   default.  Because mem is derived from clock, the wide lanes add
   width without adding states: the fixture stays a seconds-scale
   rung. *)
EXTENDS Naturals

CONSTANTS C, M, K, N, Span

VARIABLES clock, x, mem

vars == <<clock, x, mem>>

Cells == 1..N

Init == clock = 0 /\ x = 0 /\ mem = [i \in Cells |-> 0]

Tick == /\ clock' = (clock + 1) % C
        /\ x' = x
        /\ mem' = [i \in Cells |-> (clock' * (137 + i * 59)) % Span]

Bump == \E k \in 1..K :
          /\ x' = (x + k) % M
          /\ clock' = clock
          /\ mem' = mem

Next == Tick \/ Bump

Spec == Init /\ [][Next]_vars

XBounded == x < M

\* violation rung (ooc_scaled_bad.cfg): first reached at depth 15
\* (12 ticks + 3 max-stride bumps), deep enough that the capped run
\* must spill before the trace is found
NoMeet == ~(clock = 12 /\ x = 9)
=============================================================================
