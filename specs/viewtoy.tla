---------------------------- MODULE viewtoy ----------------------------
(* cfg VIEW fixture: `noise` churns independently of `x`, and the view
   collapses states to the value of `x` alone — TLC fingerprints the
   VIEW's VALUE, not the state (ConfigFileGrammar.tla:8-11), so the
   reachable count is |range of x| = 5 even though the full state space
   is 15.  Used by the serial-vs-parallel parity suite: the parallel
   engine's workers compute the view fingerprint and the parent's merge
   must dedup on it exactly like the serial engine. *)
EXTENDS Naturals

VARIABLES x, noise

Init == x = 0 /\ noise = 0

Incr == x' = (x + 1) % 5 /\ noise' = (noise + x) % 3

Jitter == x' = x /\ noise' = (noise + 1) % 3

Next == Incr \/ Jitter

Spec == Init /\ [][Next]_<<x, noise>>

V == x

TypeInv == x \in 0..4
=========================================================================
