------------------------------ MODULE MCtextbookSI ---------------------------
\* Model-checking shim for the textbook snapshot-isolation spec
\* (/root/reference/examples/textbookSnapshotIsolation.tla), encoding the
\* Toolbox model the spec documents in its header (:34-109): model-value
\* Key/TxnId sets, the full "should NEVER be violated" invariant suite, and
\* 0-ary wrappers for the parameterized invariants (cfg INVARIANT names
\* must be definitions). The documented checkable envelope is 2-3 keys x
\* 3-4 txns (:60-61).
EXTENDS textbookSnapshotIsolation

MCWellFormed == WellFormedTransactionsInHistory(history)

\* Cahill's and Bernstein's serializability formulations must agree in
\* every reachable state (:83-89) — even the non-serializable ones
MCSerializabilityEncodingsAgree ==
    CahillSerializable(history) = BernsteinSerializable(history)

\* EXPECTED to be violated (:91-96): snapshot isolation is NOT
\* serializable; finding the violation is the pass criterion
MCSerializable == CahillSerializable(history)

\* "interesting history" finders (:103-108), also expected-to-violate
MCNoInterestingHistory ==
    ~ (AtLeastNTxnsHaveCommitted(3) /\ AtLeastNTxnsHaveRead(2)
       /\ AtLeastNTxnsHaveWritten(2))

\* Seeded initial state: one transaction has already committed writes to
\* two keys, so reads of both keys are enabled from the start (a Read
\* needs a prior committed version, :297-311) — the write-skew anomaly
\* then needs only the two remaining transactions. The standard TLC
\* seeded-INIT idiom for driving the search at a known anomaly.
\* Abort-free histories only: ChooseToAbort branches at every state and
\* the write-skew anomaly contains no aborts, so pruning them shrinks the
\* seeded search by an order of magnitude (a CONSTRAINT, like raft's)
MCNoAborts == \A i \in 1..Len(history) : history[i].op /= "abort"

MCSeedTxn == CHOOSE t \in TxnId : TRUE
MCk1 == CHOOSE k \in Key : TRUE
MCk2 == CHOOSE k \in Key \ {MCk1} : TRUE
MCInitSeeded ==
    /\ history = << [op |-> "begin",  txnid |-> MCSeedTxn],
                    [op |-> "write",  txnid |-> MCSeedTxn, key |-> MCk1],
                    [op |-> "write",  txnid |-> MCSeedTxn, key |-> MCk2],
                    [op |-> "commit", txnid |-> MCSeedTxn] >>
    /\ holdingXLocks   = [txn \in TxnId |-> {}]
    /\ waitingForXLock = [txn \in TxnId |-> NoLock]

\* Tighter seed for default CI (VERDICT r2 weak #3): additionally seed
\* the second transaction's begin, its read of MCk1 and its write of MCk2
\* (with the xlock it must therefore hold — the lock-manager cross-check
\* invariants keep the seed honest). The write-skew anomaly then needs
\* only 5 more events (t3 begin / write k1 / read k2-as-of-T1 / both
\* commits, with t3 beginning before t2 commits), so the violating BFS
\* run fits the fast sweep. The looser MCInitSeeded search stays as the
\* slow-marked deeper pin.
MCTxn2 == CHOOSE t \in TxnId \ {MCSeedTxn} : TRUE
MCInitSeeded2 ==
    /\ history = << [op |-> "begin",  txnid |-> MCSeedTxn],
                    [op |-> "write",  txnid |-> MCSeedTxn, key |-> MCk1],
                    [op |-> "write",  txnid |-> MCSeedTxn, key |-> MCk2],
                    [op |-> "commit", txnid |-> MCSeedTxn],
                    [op |-> "begin",  txnid |-> MCTxn2],
                    [op |-> "read",   txnid |-> MCTxn2, key |-> MCk1,
                     ver |-> MCSeedTxn],
                    [op |-> "write",  txnid |-> MCTxn2, key |-> MCk2] >>
    /\ holdingXLocks   = [txn \in TxnId |->
                             IF txn = MCTxn2 THEN {MCk2} ELSE {}]
    /\ waitingForXLock = [txn \in TxnId |-> NoLock]
=============================================================================
