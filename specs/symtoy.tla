---------------------------- MODULE symtoy ----------------------------
(* Symmetric toy model for the device SYMMETRY canonicalizer
   (compile/symmetry2.py): a process set P grabs a token; `owner` is an
   enum lane, `used` a set-membership block, `turns` a per-process
   function — exercising the enum remap, set-lane permutation, and
   function-block permutation transforms. Counts must equal the interp
   backend's symmetry-reduced counts (cfg SYMMETRY Perms,
   reference TLC.tla:13-14 Permutations). *)
EXTENDS Naturals, FiniteSets, TLC
CONSTANTS P, None
VARIABLES owner, used, turns

Perms == Permutations(P)

Init == owner = None /\ used = {} /\ turns = [p \in P |-> 0]

Grab(p) == /\ owner' = p
           /\ used' = used \cup {p}
           /\ turns' = [turns EXCEPT ![p] = @ + 1]

Release == /\ owner /= None
           /\ owner' = None
           /\ UNCHANGED <<used, turns>>

Next == \/ owner = None /\ \E p \in P : turns[p] < 2 /\ Grab(p)
        \/ Release

Spec == Init /\ [][Next]_<<owner, used, turns>>

TypeInv == /\ owner \in P \cup {None}
           /\ used \subseteq P
           /\ turns \in [P -> 0..2]
=======================================================================
