---------------------------- MODULE constoy ----------------------------
(* cfg CONSTRAINT-discard fixture (TLC semantics, Specifying Systems
   §14): states violating the CONSTRAINT are fingerprinted so they are
   never re-processed, but they are not counted distinct, not
   invariant-checked, and not explored.  Two counters race so discards
   happen on multiple frontier chunks at once — the parity suite pins
   the parallel engine to the serial engine's exact generated/distinct
   split on the discard path. *)
EXTENDS Naturals, TLC

VARIABLES a, b

Init == a = 0 /\ b = 0

IncA == a' = a + 1 /\ b' = b

IncB == b' = b + 1 /\ a' = a

Next == IncA \/ IncB

Spec == Init /\ [][Next]_<<a, b>>

Bound == a + b <= 5

\* a CONSTRAINT whose evaluation itself raises (TLC Assert): the engines
\* must report the assert with identical counts — the successor that
\* triggers the constraint eval is already counted as generated
AssertBound == Assert(a + b <= 4, "constraint assert tripped")

TypeInv == a >= 0 /\ b >= 0
=========================================================================
