---------------------------- MODULE viewtoy_scaled ----------------------------
(* The viewtoy VIEW fixture at BENCH scale (ISSUE 6): same shape - noise
   churns under a cfg VIEW that collapses part of the state - but with
   two counters advancing by a SET of step sizes, so the view-reduced
   space is tens of thousands of states reached across a WIDE, SHALLOW
   BFS (frontier in the thousands) and states/sec measures throughput
   rather than an 11-state run's constant overhead.  The
   kernel-vs-interp bench leg runs this rung; the tiny viewtoy stays
   the parity fixture. *)
EXTENDS Naturals

CONSTANTS N, M, Q, K

VARIABLES x, y, noise

Steps == 1..K

Init == x = 0 /\ y = 0 /\ noise = 0

IncX == \E k \in Steps :
          x' = (x + k) % N /\ y' = y /\ noise' = (noise + x) % M

IncY == \E k \in Steps :
          y' = (y + k) % N /\ x' = x /\ noise' = (noise + y) % M

Jitter == x' = x /\ y' = y /\ noise' = (noise + 1) % M

Next == IncX \/ IncY \/ Jitter

Spec == Init /\ [][Next]_<<x, y, noise>>

V == <<x, y, noise \div Q>>

TypeInv == /\ x \in 0..(N - 1)
           /\ y \in 0..(N - 1)
           /\ noise \in 0..(M - 1)
=============================================================================
