---------------------------- MODULE symid ----------------------------
(* Identity-symmetry disclosure fixture (ISSUE 5 satellite): SYMMETRY
   over a SINGLETON model-value set declares only the identity
   permutation. build_canon2 (compile/symmetry2.py) and the interp's
   make_canonicalizer return None BY DESIGN here — there is no
   reduction to fall back FROM, so the backends must report
   sym=identity (NOT UNREDUCED-FALLBACK) and emit no divergence
   warning. MCPaxos's sweep line had exactly this shape. *)
EXTENDS Naturals, TLC
CONSTANTS Q
VARIABLES n

Perms == Permutations(Q)

Init == n = 0
Next == n < 3 /\ n' = n + 1
Spec == Init /\ [][Next]_n
TypeInv == n \in 0..3
=======================================================================
