---------------------------- MODULE portoy ----------------------------
(* Commuting-heavy POR fixture (ISSUE 15): every Step(p) touches only
   its own element cnt[p], so all Step arms pairwise commute — the
   element-atom footprints (analyze/independence.py) prove it and the
   --por persistent-set filter gets its measured >=30% explored-state
   reduction here.  Fire reads cnt[p1] and raises the (normally
   unchecked) flag, giving the _bad cfg an invariant violation that the
   reduced search must still find; with all counters maxed and the
   flag raised the model deadlocks, giving the default cfg its
   deadlock rung. *)
EXTENDS Naturals
CONSTANTS Procs, Max, P1
VARIABLES cnt, flag

Init == cnt = [p \in Procs |-> 0] /\ flag = FALSE

Step(p) == /\ cnt[p] < Max
           /\ cnt' = [cnt EXCEPT ![p] = @ + 1]
           /\ UNCHANGED flag

Fire == /\ cnt[P1] = Max
        /\ ~flag
        /\ flag' = TRUE
        /\ UNCHANGED cnt

Next == (\E p \in Procs : Step(p)) \/ Fire

Spec == Init /\ [][Next]_<<cnt, flag>>

Bounded == \A p \in Procs : cnt[p] =< Max
NoFire == ~flag
=======================================================================
