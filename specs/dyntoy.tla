---------------------------- MODULE dyntoy ----------------------------
(* Derived interp-arms fixture (ISSUE 15): both arms quantify over the
   state variable msgs with slot-axis shapes the grounder cannot size —
   Pair's multi-binder dynamic \E and Relay's nested dynamic \E — so
   every arm demotes to the interpreter at BUILD time, and the
   analyze/verdicts.py taxonomy predicts both with the exact ground.py
   reason strings (DYN_SHAPE_MSG / DYN_NESTED_MSG).  The corpus
   manifest pins this case mode="interp-arms" with pin_derived=True:
   the predictor, not a measured pin, skips the futile builds, and a
   predictor regression fails the sweep loudly. *)
EXTENDS Naturals, FiniteSets
CONSTANTS N
VARIABLES msgs, acks

Init == msgs = 1..N /\ acks = {}

Pair == \E m \in msgs, k \in msgs :
          /\ m < k
          /\ acks' = acks \cup {m}
          /\ UNCHANGED msgs

Relay == \E m \in msgs : \E k \in msgs :
           /\ m < k
           /\ acks' = acks \cup {k}
           /\ UNCHANGED msgs

Next == Pair \/ Relay

Spec == Init /\ [][Next]_<<msgs, acks>>

AcksInMsgs == acks \subseteq msgs
=======================================================================
